"""tf_operator_tpu.analysis: the concurrency lint and the seams that make
its rules satisfiable (utils/locks.py named factories + InstrumentedLock,
utils/clock.py injectable wall clock).

Three layers:
  1. self-tests — each rule fires on a known-bad fixture at the pinned
     rule id + file:line, and header-line suppressions silence it;
  2. the package pin — the whole tf_operator_tpu package has ZERO
     findings (this is the CI gate: a new bare lock, wall-clock read,
     silent swallow, anonymous thread, or unguarded mutation fails here);
  3. seam behavior — FakeClock swaps, lock factories, and the
     InstrumentedLock registry (acquisition order, hold times, inversion
     detection).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from tf_operator_tpu import analysis
from tf_operator_tpu.utils import clock, locks

REPO = Path(__file__).resolve().parent.parent
PACKAGE_DIR = REPO / "tf_operator_tpu"
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


# ---------------------------------------------------------------------------
# 1. rule self-tests: one known-bad fixture per rule, pinned to file:line


@pytest.mark.parametrize(
    "fixture, rel_path, rule, line",
    [
        ("bad_bare_lock.py", "bad_bare_lock.py", "bare-lock", 6),
        ("bad_wall_clock.py", "runtime/bad_wall_clock.py", "wall-clock", 9),
        ("bad_swallow.py", "bad_swallow.py", "swallow", 7),
        ("bad_thread.py", "bad_thread.py", "thread-hygiene", 7),
        ("bad_guarded.py", "bad_guarded.py", "guarded-by", 12),
        ("bad_requires_lock.py", "bad_requires_lock.py", "guarded-by", 15),
        ("bad_lock_order.py", "bad_lock_order.py", "lock-order", 16),
        ("bad_guarded_interproc.py", "bad_guarded_interproc.py",
         "guarded-by-interproc", 17),
        ("bad_atomicity.py", "bad_atomicity.py", "atomicity", 19),
        ("bad_sleep_poll.py", "tests/bad_sleep_poll.py", "sleep-poll", 9),
        ("bad_statuswriter_bypass.py", "bad_statuswriter_bypass.py",
         "statuswriter-bypass", 8),
        ("bad_ownership_fence.py", "bad_ownership_fence.py",
         "ownership-fence", 13),
        ("bad_state_machine.py", "bad_state_machine.py", "state-machine", 9),
        ("bad_wire_roundtrip.py", "bad_wire_roundtrip.py",
         "wire-roundtrip", 11),
        ("bad_knob_chain.py", "bad_knob_chain.py", "knob-chain", 9),
        ("bad_metric_doc.py", "bad_metric_doc.py", "metric-doc", 14),
        ("bad_condition_unset.py", "bad_condition_unset.py",
         "state-machine", 10),
    ],
)
def test_rule_fires_exactly_once(fixture, rel_path, rule, line):
    findings = analysis.check_file(str(FIXTURES / fixture), rel_path=rel_path)
    assert [(f.rule, f.path, f.line) for f in findings] == [
        (rule, rel_path, line)
    ], "\n".join(f.render() for f in findings)


def test_wall_clock_rule_is_scope_limited():
    """The same source is clean outside runtime//controller//server."""
    path = str(FIXTURES / "bad_wall_clock.py")
    assert analysis.check_file(path, rel_path="train/bad_wall_clock.py") == []
    for scope in ("runtime", "controller", "server"):
        assert analysis.check_file(path, rel_path=f"{scope}/x.py"), scope
    # scope must survive a lint root ABOVE the package (vendored layouts)
    assert analysis.check_file(
        path, rel_path="tf_operator_tpu/runtime/bad_wall_clock.py"), "parent root"


def test_unparseable_file_is_a_finding_not_a_crash(tmp_path):
    findings = analysis.check_source("def f(:\n", "broken.py")
    assert [(f.rule, f.line) for f in findings] == [("parse-error", 1)]
    # and through the CLI: rendered finding + nonzero exit, no traceback
    pkg = tmp_path / "brokenpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("def f(:\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(pkg)],
        capture_output=True, text=True,
        env={**os.environ,
             "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", "")},
    )
    assert proc.returncode == 1
    assert "[parse-error]" in proc.stdout
    assert "Traceback" not in proc.stderr


def test_header_line_suppressions_silence_every_rule():
    findings = analysis.check_file(
        str(FIXTURES / "suppressed_ok.py"),
        rel_path="runtime/suppressed_ok.py",
    )
    assert findings == [], "\n".join(f.render() for f in findings)


def test_suppression_on_multiline_statement_header():
    """The documented contract: the allow goes on the line the STATEMENT
    starts on, even when the violating expression sits on a continuation
    line (formatter-wrapped assignments)."""
    src = (
        "import threading\n"
        "_l = (  # lint: allow(bare-lock)\n"
        "    threading.Lock())\n"
    )
    assert analysis.check_source(src, "x.py") == []
    unsuppressed = src.replace("  # lint: allow(bare-lock)", "")
    assert [f.rule for f in analysis.check_source(unsuppressed, "x.py")] == ["bare-lock"]


def test_swallow_rule_accepts_logging_and_reraise():
    logged = (
        "import logging\n"
        "def f(op):\n"
        "    try:\n"
        "        op()\n"
        "    except Exception as e:\n"
        "        logging.getLogger('x').warning('failed: %s', e)\n"
    )
    reraised = (
        "def f(op):\n"
        "    try:\n"
        "        op()\n"
        "    except Exception:\n"
        "        raise\n"
    )
    bare = (
        "def f(op):\n"
        "    try:\n"
        "        op()\n"
        "    except:\n"
        "        return None\n"
    )
    assert analysis.check_source(logged, "x.py") == []
    assert analysis.check_source(reraised, "x.py") == []
    assert [f.rule for f in analysis.check_source(bare, "x.py")] == ["swallow"]


def test_thread_rule_requires_both_name_and_daemon():
    named_only = "import threading\nt = threading.Thread(target=print, name='tpujob-x')\n"
    daemon_only = "import threading\nt = threading.Thread(target=print, daemon=True)\n"
    both = "import threading\nt = threading.Thread(target=print, name='tpujob-x', daemon=True)\n"
    assert [f.rule for f in analysis.check_source(named_only, "x.py")] == ["thread-hygiene"]
    assert [f.rule for f in analysis.check_source(daemon_only, "x.py")] == ["thread-hygiene"]
    assert analysis.check_source(both, "x.py") == []


def test_import_aliases_cannot_evade_rules():
    """`from time import time`, `import time as t`, `import threading as
    th`, and `from threading import Lock` are the same violations in
    different spelling."""
    from_import = (
        "from time import time\n"
        "def stamp():\n"
        "    return time()\n"
    )
    module_alias = (
        "import time as t\n"
        "def stamp():\n"
        "    return t.time()\n"
    )
    threading_alias = (
        "import threading as th\n"
        "_l = th.Lock()\n"
        "_t = th.Thread(target=print)\n"
    )
    renamed_ctor = (
        "from threading import Lock as L\n"
        "_l = L()\n"
    )
    assert [f.rule for f in analysis.check_source(from_import, "runtime/x.py")] == ["wall-clock"]
    assert [f.rule for f in analysis.check_source(module_alias, "runtime/x.py")] == ["wall-clock"]
    assert sorted(f.rule for f in analysis.check_source(threading_alias, "x.py")) == [
        "bare-lock", "thread-hygiene"]
    assert [f.rule for f in analysis.check_source(renamed_ctor, "x.py")] == ["bare-lock"]
    # the alias spellings stay clean out of wall-clock scope
    assert analysis.check_source(from_import, "train/x.py") == []


def test_timer_rule_requires_postconstruction_name_and_daemon():
    """threading.Timer (a Thread subclass with no name=/daemon= kwargs)
    must get both set right after construction."""
    bad = (
        "import threading\n"
        "def arm(fn):\n"
        "    t = threading.Timer(1.0, fn)\n"
        "    t.start()\n"
    )
    unbound = (
        "import threading\n"
        "def arm(fn):\n"
        "    threading.Timer(1.0, fn).start()\n"
    )
    good = (
        "import threading\n"
        "def arm(fn):\n"
        "    t = threading.Timer(1.0, fn)\n"
        "    t.name = 'tpujob-requeue'\n"
        "    t.daemon = True\n"
        "    t.start()\n"
    )
    assert [f.rule for f in analysis.check_source(bad, "x.py")] == ["thread-hygiene"]
    assert [f.rule for f in analysis.check_source(unbound, "x.py")] == ["thread-hygiene"]
    assert analysis.check_source(good, "x.py") == []


def test_guarded_by_module_globals():
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "_lock = locks.new_lock('m')\n"
        "_cache = None  # guarded-by: _lock\n"
        "def fill(v):\n"
        "    global _cache\n"
        "    _cache = v\n"
        "def fill_safely(v):\n"
        "    global _cache\n"
        "    with _lock:\n"
        "        _cache = v\n"
        "def local_shadow(v):\n"
        "    _cache = v\n"       # local bind, not the module global
        "    return _cache\n"
    )
    findings = analysis.check_source(src, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("guarded-by", 6)]


def test_guarded_by_module_globals_inplace_mutators():
    """`_pending.append(v)` needs no `global` statement, so the rule must
    check in-place mutator calls and subscript writes on guarded globals —
    unless the function locally shadows the name."""
    bad_append = (
        "_lock = object()\n"
        "_pending = []  # guarded-by: _lock\n"
        "def enqueue(v):\n"
        "    _pending.append(v)\n"
    )
    bad_subscript = (
        "_lock = object()\n"
        "_cache = {}  # guarded-by: _lock\n"
        "def put(k, v):\n"
        "    _cache[k] = v\n"
    )
    good_locked = (
        "_lock = object()\n"
        "_pending = []  # guarded-by: _lock\n"
        "def enqueue(v):\n"
        "    with _lock:\n"
        "        _pending.append(v)\n"
    )
    local_shadow = (
        "_lock = object()\n"
        "_pending = []  # guarded-by: _lock\n"
        "def scratch(v):\n"
        "    _pending = []\n"
        "    _pending.append(v)\n"
    )
    assert [(f.rule, f.line) for f in analysis.check_source(bad_append, "m.py")] == [("guarded-by", 4)]
    assert [(f.rule, f.line) for f in analysis.check_source(bad_subscript, "m.py")] == [("guarded-by", 4)]
    assert analysis.check_source(good_locked, "m.py") == []
    assert analysis.check_source(local_shadow, "m.py") == []


def test_guarded_by_module_globals_in_nested_blocks():
    """Top-level mutations hiding inside if/try/with bodies are checked
    too; a module-level `with _lock:` counts as held."""
    flagged = (
        "import os\n"
        "_lock = object()\n"
        "_cache = None  # guarded-by: _lock\n"
        "if os.environ.get('PRELOAD'):\n"
        "    _cache = 1\n"
    )
    held = (
        "_lock = object()\n"
        "_cache = None  # guarded-by: _lock\n"
        "with _lock:\n"
        "    _cache = 1\n"
    )
    findings = analysis.check_source(flagged, "m.py")
    assert [(f.rule, f.line) for f in findings] == [("guarded-by", 5)]
    assert analysis.check_source(held, "m.py") == []


def test_guarded_by_exempts_declaring_init():
    """The declaring __init__ writes lock-free by design (no concurrent
    reader can hold a reference yet)."""
    src = (
        "class C:\n"
        "    def __init__(self, lock):\n"
        "        self._lock = lock\n"
        "        self._state = {}  # guarded-by: _lock\n"
        "        self._state['a'] = 1\n"
    )
    assert analysis.check_source(src, "x.py") == []


def test_guarded_by_checks_closures_defined_in_init():
    """A closure built in __init__ (watch handler, timer callback) runs
    later, on other threads — it gets no __init__ exemption and no
    lock-held credit from its definition site."""
    src = (
        "class C:\n"
        "    def __init__(self, lock, bus):\n"
        "        self._lock = lock\n"
        "        self._items = []  # guarded-by: _lock\n"
        "        def handler(ev):\n"
        "            self._items.append(ev)\n"
        "        bus.subscribe(handler)\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [(f.rule, f.line) for f in findings] == [("guarded-by", 6)]


def test_lock_order_sees_through_call_chains():
    """Holding A while *calling* a helper that acquires B is the same edge
    as holding A while nesting `with B:` — the cycle must be found even
    when one leg is interprocedural."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = locks.new_lock('a')\n"
        "        self._b = locks.new_lock('b')\n"
        "    def _take_b(self):\n"
        "        with self._b:\n"
        "            pass\n"
        "    def forward(self):\n"
        "        with self._a:\n"
        "            self._take_b()\n"
        "    def backward(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [f.rule for f in findings] == ["lock-order"], "\n".join(
        f.render() for f in findings)
    assert "C._a" in findings[0].message and "C._b" in findings[0].message
    # consistent order in both methods: no cycle
    clean = src.replace(
        "    def backward(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n",
        "    def backward(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n",
    )
    assert analysis.check_source(clean, "x.py") == []


def test_lock_order_suppressed_by_any_edge_allow():
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = locks.new_lock('a')\n"
        "        self._b = locks.new_lock('b')\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:  # lint: allow(lock-order) — justified\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    assert analysis.check_source(src, "x.py") == []


def test_guarded_interproc_respects_requires_lock_and_locked_callers():
    """A helper reading a guarded field is clean when every chain to it
    holds the lock (annotation or call-site `with`); it fires only when an
    unlocked chain exists."""
    locked_chain = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.new_lock('c')\n"
        "        self._items = []  # guarded-by: _lock\n"
        "    def snapshot(self):\n"
        "        with self._lock:\n"
        "            return self._collect()\n"
        "    def _collect(self):\n"
        "        return list(self._items)\n"
    )
    assert analysis.check_source(locked_chain, "x.py") == []
    unlocked_entry = locked_chain.replace(
        "    def snapshot(self):\n"
        "        with self._lock:\n"
        "            return self._collect()\n",
        "    def snapshot(self):\n"
        "        return self._collect()\n",
    )
    findings = analysis.check_source(unlocked_entry, "x.py")
    assert [f.rule for f in findings] == ["guarded-by-interproc"]
    assert "C.snapshot -> C._collect" in findings[0].message
    # suppression on the access line silences it
    suppressed = unlocked_entry.replace(
        "        return list(self._items)\n",
        "        return list(self._items)  # lint: allow(guarded-by-interproc) — torn read is benign here\n",
    )
    assert analysis.check_source(suppressed, "x.py") == []


def test_guarded_interproc_tracks_locks_inside_except_handlers():
    """An except handler's `with self._lock:` must count as held — the
    handler body is statements like any other (ExceptHandler is not an
    ast.stmt, which once dropped held tracking there)."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.new_lock('c')\n"
        "        self._items = []  # guarded-by: _lock\n"
        "    def snapshot(self, op):\n"
        "        try:\n"
        "            return op()\n"
        "        except ValueError:\n"
        "            with self._lock:\n"
        "                return list(self._items)\n"
    )
    assert analysis.check_source(src, "x.py") == []
    unlocked = src.replace(
        "            with self._lock:\n"
        "                return list(self._items)\n",
        "            return list(self._items)\n",
    )
    assert [f.rule for f in analysis.check_source(unlocked, "x.py")] == [
        "guarded-by-interproc"]


def test_guarded_interproc_reports_subscript_slice_read_once():
    """A guarded-field read in a subscript slice must produce ONE finding,
    not one from the write-target scan plus one from the child scan."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.new_lock('c')\n"
        "        self._idx = 0  # guarded-by: _lock\n"
        "        self._map = {}\n"
        "    def put(self, v):\n"
        "        self._map[self._idx] = v\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [(f.rule, f.line) for f in findings] == [
        ("guarded-by-interproc", 8)], "\n".join(f.render() for f in findings)


def test_lock_order_allow_does_not_hide_other_cycles():
    """Suppressing one edge removes only that edge from the graph: a
    DIFFERENT cycle sharing a lock must still report."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = locks.new_lock('a')\n"
        "        self._b = locks.new_lock('b')\n"
        "        self._c = locks.new_lock('c')\n"
        "    def ab(self):\n"
        "        with self._a:\n"
        "            with self._b:  # lint: allow(lock-order) — justified\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
        "    def ac(self):\n"
        "        with self._a:\n"
        "            with self._c:\n"
        "                pass\n"
        "    def ca(self):\n"
        "        with self._c:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [f.rule for f in findings] == ["lock-order"], "\n".join(
        f.render() for f in findings)
    assert "C._c" in findings[0].message  # the a<->c cycle survived


def test_lock_order_sees_multi_item_with():
    """`with self._a, self._b:` acquires b while holding a — the same
    edge as the nested form, and the same deadlock against b->a."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = locks.new_lock('a')\n"
        "        self._b = locks.new_lock('b')\n"
        "    def ab(self):\n"
        "        with self._a, self._b:\n"
        "            pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [f.rule for f in findings] == ["lock-order"], "\n".join(
        f.render() for f in findings)


def test_lock_order_allow_covers_only_its_own_site():
    """Two sites witnessing the SAME edge: an allow on one must not
    silence the cycle through the other, unjustified site."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._a = locks.new_lock('a')\n"
        "        self._b = locks.new_lock('b')\n"
        "    def ab_ok(self):\n"
        "        with self._a:\n"
        "            with self._b:  # lint: allow(lock-order) — justified\n"
        "                pass\n"
        "    def ab_bad(self):\n"
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._b:\n"
        "            with self._a:\n"
        "                pass\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [f.rule for f in findings] == ["lock-order"], "\n".join(
        f.render() for f in findings)
    # suppressing BOTH forward sites removes the edge and the cycle
    both = src.replace(
        "        with self._a:\n"
        "            with self._b:\n"
        "                pass\n",
        "        with self._a:\n"
        "            with self._b:  # lint: allow(lock-order) — also ok\n"
        "                pass\n",
    )
    assert analysis.check_source(both, "x.py") == []


def test_atomicity_sees_base_class_guarded_fields():
    """Check-then-act in a subclass on a field the BASE declared
    guarded must fire like it would in the base itself."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class Base:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.new_lock('base')\n"
        "        self._slots = {}  # guarded-by: _lock\n"
        "class Child(Base):\n"
        "    def put_once(self, key, value):\n"
        "        with self._lock:\n"
        "            present = key in self._slots\n"
        "        if not present:\n"
        "            with self._lock:\n"
        "                self._slots[key] = value\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [(f.rule, f.line) for f in findings] == [("atomicity", 12)], \
        "\n".join(f.render() for f in findings)


def test_sleep_poll_ignores_nested_function_scopes():
    """A sleep inside a callback DEFINED in the loop never runs in the
    loop (no finding); a compare hidden in a nested def bounds nothing
    (still a finding)."""
    callback_sleep = (
        "import time\n"
        "def collect(done, cbs):\n"
        "    while not done():\n"
        "        cbs.append(lambda: time.sleep(1))\n"
        "        done = done\n"
    )
    hidden_compare = (
        "import time\n"
        "def wait(p):\n"
        "    while not p():\n"
        "        def bound():\n"
        "            return time.time() < 99\n"
        "        time.sleep(0.01)\n"
    )
    assert analysis.check_source(callback_sleep, "tests/x.py") == []
    assert [f.rule for f in analysis.check_source(hidden_compare,
                                                  "tests/x.py")] == [
        "sleep-poll"]


def test_sleep_poll_reports_nested_unbounded_loops_once():
    src = (
        "import time\n"
        "def wait(p):\n"
        "    while True:\n"
        "        while not p():\n"
        "            time.sleep(0.01)\n"
    )
    findings = analysis.check_source(src, "tests/x.py")
    assert [(f.rule, f.line) for f in findings] == [("sleep-poll", 5)]


def test_guarded_interproc_does_not_double_report_writes():
    """Unprotected WRITES stay the intraprocedural rule's findings — the
    interprocedural rule must not duplicate them."""
    src = (
        "from tf_operator_tpu.utils import locks\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.new_lock('c')\n"
        "        self._items = []  # guarded-by: _lock\n"
        "    def add(self, v):\n"
        "        self._items.append(v)\n"
    )
    findings = analysis.check_source(src, "x.py")
    assert [f.rule for f in findings] == ["guarded-by"]


def test_atomicity_accepts_revalidated_double_check():
    """Re-reading the field inside the write's critical section is the
    documented fix; the rule must not fire on it (the package's gang
    scheduler admission uses exactly this shape)."""
    findings = analysis.check_file(str(FIXTURES / "bad_atomicity.py"))
    assert [(f.rule, f.line) for f in findings] == [("atomicity", 19)]
    # i.e. put_checked (line 26+) produced nothing — pinned by exactly-once


def test_sleep_poll_scope_and_shapes():
    bounded = (
        "import time\n"
        "def wait(p, timeout=5.0):\n"
        "    deadline = time.time() + timeout\n"
        "    while time.time() < deadline:\n"
        "        if p():\n"
        "            return True\n"
        "        time.sleep(0.01)\n"
        "    return p()\n"
    )
    unbounded = (
        "import time\n"
        "def wait(p):\n"
        "    while not p():\n"
        "        time.sleep(0.01)\n"
    )
    sync_until_shape = (  # deadline check in the body, `while True` head
        "import time\n"
        "def wait(p, timeout=5.0):\n"
        "    deadline = time.time() + timeout\n"
        "    while True:\n"
        "        if p():\n"
        "            return True\n"
        "        if time.time() >= deadline:\n"
        "            return False\n"
        "        time.sleep(0.01)\n"
    )
    bounded_for = (
        "import time\n"
        "def settle():\n"
        "    for _ in range(3):\n"
        "        time.sleep(0.01)\n"
    )
    assert analysis.check_source(bounded, "tests/x.py") == []
    assert analysis.check_source(sync_until_shape, "tests/x.py") == []
    assert analysis.check_source(bounded_for, "tests/x.py") == []
    assert [f.rule for f in analysis.check_source(unbounded, "tests/x.py")] \
        == ["sleep-poll"]
    # test_*.py basenames are in scope even without a tests/ dir segment
    assert [f.rule for f in analysis.check_source(unbounded, "test_x.py")] \
        == ["sleep-poll"]
    # control-plane code is out of scope (its loops block on events)
    assert analysis.check_source(unbounded, "runtime/x.py") == []
    # from-imported alias can't evade
    aliased = unbounded.replace("import time\n", "from time import sleep\n")
    aliased = aliased.replace("time.sleep", "sleep")
    assert [f.rule for f in analysis.check_source(aliased, "tests/x.py")] \
        == ["sleep-poll"]


def test_tests_tree_has_zero_sleep_poll_findings():
    """The satellite pin: the repo's own test suite contains no unbounded
    sleep-polls (known-bad fixtures excluded)."""
    findings = [
        f for f in analysis.check_package(
            str(REPO / "tests"), exclude_dirs=["lint_fixtures"])
        if f.rule == analysis.RULE_SLEEP_POLL
    ]
    assert findings == [], "\n".join(f.render("tests/") for f in findings)


def test_statuswriter_bypass_exempts_writer_class_only():
    """The rule keys on the RECEIVER shape (`cluster.` / `.cluster.`) and
    exempts only code lexically inside a CoalescingStatusWriter class —
    the sanctioned path's own flush."""
    inside = (
        "class CoalescingStatusWriter:\n"
        "    def flush(self, ns, name, status):\n"
        "        self.cluster.update_job_status(ns, name, status)\n"
    )
    outside = (
        "def mark(cluster, ns, name, status):\n"
        "    cluster.update_job_status(ns, name, status)\n"
    )
    other_receiver = (
        "def mark(writer, job):\n"
        "    writer.update_job_status(job)\n"
    )
    assert analysis.check_source(inside, "runtime/x.py") == []
    assert [f.rule for f in analysis.check_source(outside, "runtime/x.py")] \
        == ["statuswriter-bypass"]
    # a non-cluster receiver is somebody else's method, not a wire PUT
    assert analysis.check_source(other_receiver, "runtime/x.py") == []


def test_ownership_fence_arms_only_in_federated_modules():
    """A bare work_queue.add is fine in a module that never touches the
    shard manager; the identical code fires once the module is federated,
    and an owns()/owns_key() call anywhere in the function fences it."""
    unfederated = (
        "class C:\n"
        "    def enqueue(self, key):\n"
        "        self.work_queue.add(key)\n"
    )
    federated = "class C:\n    shard_manager = None\n" + (
        "    def enqueue(self, key):\n"
        "        self.work_queue.add(key)\n"
    )
    fenced = "class C:\n    shard_manager = None\n" + (
        "    def enqueue(self, key):\n"
        "        if self.owns_key(key):\n"
        "            self.work_queue.add(key)\n"
    )
    assert analysis.check_source(unfederated, "controller/x.py") == []
    assert [f.rule for f in analysis.check_source(federated, "controller/x.py")] \
        == ["ownership-fence"]
    assert analysis.check_source(fenced, "controller/x.py") == []


def test_ownership_fence_tracks_queue_aliases():
    """A pop through a variable assigned from a work_queue call is still
    a worker pop and needs the fence."""
    src = (
        "class C:\n"
        "    shard_manager = None\n"
        "    def pop(self, shard):\n"
        "        q = self.work_queue.shard(shard)\n"
        "        return q.get(timeout=0.5)\n"
    )
    assert [f.rule for f in analysis.check_source(src, "controller/x.py")] \
        == ["ownership-fence"]


def test_state_machine_rejects_nonliteral_reasons():
    """Literal reasons are checked against the declared edge set; a
    non-literal reason makes the edge set uncheckable and is itself a
    finding.  Condition types without a declared machine are unchecked."""
    nonliteral = (
        "def f(status, conditions, JobConditionType, why):\n"
        "    conditions.update_job_conditions(\n"
        "        status, JobConditionType.RESIZING, why, 'msg')\n"
    )
    declared_kwargs = (
        "def f(status, conditions, JobConditionType):\n"
        "    conditions.clear_condition(\n"
        "        status, ctype=JobConditionType.RESIZING,\n"
        "        reason='RunningResized', message='msg')\n"
    )
    wrong_verb = (
        "def f(status, conditions, JobConditionType):\n"
        "    conditions.clear_condition(\n"
        "        status, JobConditionType.RESIZING, 'JobResizing', 'msg')\n"
    )
    assert [f.rule for f in analysis.check_source(nonliteral, "x.py")] \
        == ["state-machine"]
    assert analysis.check_source(declared_kwargs, "x.py") == []
    # JobResizing is a SET-edge reason; using it on a clear is off-machine
    assert [f.rule for f in analysis.check_source(wrong_verb, "x.py")] \
        == ["state-machine"]


def test_state_machines_cover_every_condition_type():
    """Every JobConditionType member has a declared machine — the rule
    verifies 'every declared condition is set somewhere' package-wide, so
    an uncovered member would silently escape both checks."""
    from tf_operator_tpu.api.types import JobConditionType

    assert set(analysis.CONDITION_STATE_MACHINES) \
        == {m.name for m in JobConditionType}
    for name, machine in analysis.CONDITION_STATE_MACHINES.items():
        assert set(machine) == {"set", "clear"}, name
        assert machine["set"], f"{name} has no set-edge reasons"


def test_contract_exempt_annotation_is_rule_scoped():
    """`# contract: exempt(<rule>)` silences exactly the named rule at
    the annotated site; a different rule name there changes nothing."""
    lopsided = (
        "from dataclasses import dataclass\n"
        "@dataclass\n"
        "class W:\n"
        "    a: int = 0{ann}\n"
        "def w_to_dict(w: W) -> dict:\n"
        "    return {{'a': w.a}}\n"
        "def w_from_dict(d: dict) -> W:\n"
        "    return W()\n"
    )
    hit = analysis.check_source(lopsided.format(ann=""), "x.py")
    assert [f.rule for f in hit] == ["wire-roundtrip"]
    exempt = lopsided.format(ann="  # contract: exempt(wire-roundtrip)")
    assert analysis.check_source(exempt, "x.py") == []
    wrong = lopsided.format(ann="  # contract: exempt(knob-chain)")
    assert [f.rule for f in analysis.check_source(wrong, "x.py")] \
        == ["wire-roundtrip"]


def test_knob_chain_requires_full_knob_name():
    """A bare 'TPUJOB_' prefix string (env scrubbers iterate prefixes) and
    prose mentioning a knob are not knob producers/consumers."""
    scrubber = (
        "def scrub(env):\n"
        "    return {k: v for k, v in env.items()\n"
        "            if not k.startswith('TPUJOB_')}\n"
    )
    assert analysis.check_source(scrubber, "x.py") == []
    produced_only = (
        "def inject(env):\n"
        "    env['TPUJOB_ONLY_PRODUCED'] = '1'\n"
    )
    assert [f.rule for f in analysis.check_source(produced_only, "x.py")] \
        == ["knob-chain"]


def test_rule_doc_and_severity_metadata():
    """Every rule id resolves to a docs anchor; dynamic (race/explore-*)
    findings share the race-detector section.  Advisory rules are
    warnings, everything else an error."""
    assert len(analysis.ALL_RULES) == 20  # 15 source + 4 hlo + parse-error
    for rule in (analysis.RULE_STATUSWRITER_BYPASS,
                 analysis.RULE_OWNERSHIP_FENCE,
                 analysis.RULE_STATE_MACHINE,
                 analysis.RULE_WIRE_ROUNDTRIP,
                 analysis.RULE_KNOB_CHAIN,
                 analysis.RULE_METRIC_DOC):
        assert rule in analysis.ALL_RULES
        assert analysis.rule_doc(rule) == f"docs/static-analysis.md#{rule}"
        assert analysis.RULE_SEVERITY.get(rule, "error") == "error"
    assert analysis.rule_doc(analysis.RULE_RACE) \
        == "docs/static-analysis.md#the-race-detector"
    assert analysis.rule_doc("explore-deadlock") \
        == "docs/static-analysis.md#the-race-detector"
    assert analysis.RULE_SEVERITY[analysis.RULE_SLEEP_POLL] == "warning"
    assert analysis.RULE_RACE not in analysis.ALL_RULES  # dynamic-only


# ---------------------------------------------------------------------------
# 2. the package pin — the CI gate


def test_package_has_zero_findings():
    findings = analysis.check_package(str(PACKAGE_DIR))
    assert findings == [], (
        f"{len(findings)} lint finding(s) in tf_operator_tpu "
        "(see docs/static-analysis.md):\n"
        + "\n".join(f.render("tf_operator_tpu/") for f in findings)
    )


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")

    clean = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "0 finding(s)" in clean.stdout

    bad = tmp_path / "badpkg"
    bad.mkdir()
    (bad / "__init__.py").write_text(
        "import threading\n_l = threading.Lock()\n"
    )
    dirty = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(bad)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert dirty.returncode == 1
    assert "[bare-lock]" in dirty.stdout
    assert "__init__.py:2" in dirty.stdout


def test_cli_json_output_schema(tmp_path):
    """--json writes the documented machine-readable findings document
    (docs/static-analysis.md): version 2 adds a `schema` identifier and
    per-finding `severity` + `rule_doc` — strictly additive, so every v1
    field is still present with its v1 meaning."""
    import json

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    bad = tmp_path / "badpkg"
    bad.mkdir()
    (bad / "__init__.py").write_text(
        "import threading\n_l = threading.Lock()\n")
    out = tmp_path / "findings.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(bad),
         "--json", str(out)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 1
    doc = json.loads(out.read_text())
    assert doc["version"] == analysis.FINDINGS_JSON_VERSION == 2
    assert doc["schema"] == analysis.FINDINGS_JSON_SCHEMA
    assert doc["count"] == 1
    assert doc["findings"] == [{
        "rule": "bare-lock", "path": "__init__.py", "line": 2,
        "message": doc["findings"][0]["message"],
        "severity": "error",
        "rule_doc": "docs/static-analysis.md#bare-lock",
    }]
    assert "new_lock" in doc["findings"][0]["message"]
    # a v1 reader — one that only touches the v1 fields — still works
    v1_view = {k: doc["findings"][0][k]
               for k in ("rule", "path", "line", "message")}
    assert v1_view["rule"] == "bare-lock" and v1_view["line"] == 2
    # clean run still writes the document (count 0) — CI parses it blindly
    clean_out = tmp_path / "clean.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--json", str(clean_out)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(clean_out.read_text())
    assert doc["count"] == 0 and doc["findings"] == []


def test_cli_rules_filter_and_exclude(tmp_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    pkg = tmp_path / "tests"
    pkg.mkdir()
    (pkg / "test_poll.py").write_text(
        "import time\nimport threading\n"
        "_l = threading.Lock()\n"          # bare-lock: filtered out
        "def wait(p):\n"
        "    while not p():\n"
        "        time.sleep(0.01)\n"       # sleep-poll: reported
    )
    fixtures = pkg / "lint_fixtures"
    fixtures.mkdir()
    (fixtures / "bad.py").write_text(
        "import time\n"
        "def wait(p):\n"
        "    while not p():\n"
        "        time.sleep(0.01)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(pkg),
         "--rules", "sleep-poll", "--exclude", "lint_fixtures"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 1
    assert "[sleep-poll]" in proc.stdout
    assert "[bare-lock]" not in proc.stdout      # filtered
    assert "lint_fixtures" not in proc.stdout    # excluded
    assert "1 finding(s)" in proc.stdout
    # unknown rule ids are an error, not a silent no-op filter
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(pkg),
         "--rules", "no-such-rule"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode != 0
    assert "no-such-rule" in proc.stderr
    # parse-error survives any filter: an unparseable file is never clean
    (pkg / "test_broken.py").write_text("def f(:\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis", str(pkg),
         "--rules", "bare-lock", "--exclude", "lint_fixtures"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 1
    assert "[parse-error]" in proc.stdout


def test_cli_manifest_stdout_and_json(tmp_path):
    """--manifest emits the canonical interface manifest: version 1,
    stable schema id, and the four contract surfaces.  --json writes the
    same document byte-for-byte regenerable (sorted keys)."""
    import json

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--manifest"],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == 1
    assert doc["schema"] == "tf-operator-tpu/interface-manifest"
    for surface in ("wire", "knobs", "metrics", "conditions"):
        assert doc[surface], f"empty {surface} surface"

    out = tmp_path / "manifest.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--manifest", "--json", str(out)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(out.read_text()) == doc


def test_cli_manifest_diff_gate(tmp_path):
    """--diff exits 0 on a matching committed snapshot, 1 with rendered
    drift lines on a tampered one; --diff without --manifest is a usage
    error (exit 2)."""
    import json

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    committed = REPO / "docs" / "interface-manifest.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--manifest", "--diff", str(committed)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "interface manifest matches" in proc.stdout

    doc = json.loads(committed.read_text())
    doc["knobs"]["TPUJOB_NO_SUCH_KNOB"] = {
        "constant": None, "consumers": [], "exempt": False,
        "producers": []}
    tampered = tmp_path / "stale-manifest.json"
    tampered.write_text(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--manifest", "--diff", str(tampered)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 1
    assert "manifest drift:" in proc.stdout
    assert "TPUJOB_NO_SUCH_KNOB" in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.analysis",
         str(PACKAGE_DIR), "--diff", str(committed)],
        capture_output=True, text=True, env=env, cwd=str(tmp_path),
    )
    assert proc.returncode == 2
    assert "--diff requires --manifest" in proc.stderr


def test_committed_manifest_matches_regeneration():
    """The PR-review contract: docs/interface-manifest.json is exactly
    what --manifest regenerates from the package today."""
    import json

    contract = analysis.package_contract(str(PACKAGE_DIR))
    committed = json.loads(
        (REPO / "docs" / "interface-manifest.json").read_text())
    assert analysis.contract.manifest_dict(contract) == committed


# ---------------------------------------------------------------------------
# 3. seam behavior


def test_fake_clock_swaps_and_restores():
    real_before = clock.now()
    with clock.use(clock.FakeClock(1000.0)) as fake:
        assert clock.now() == 1000.0
        fake.advance(600)
        assert clock.now() == 1600.0
        fake.set_time(50.0)
        assert clock.now() == 50.0
        with pytest.raises(ValueError):
            fake.advance(-1)
    assert clock.now() >= real_before  # real clock restored


def test_fake_clock_drives_lease_expiry():
    """The seam in action: lease expiry without sleeping."""
    from tf_operator_tpu.runtime.cluster import InMemoryCluster

    with clock.use(clock.FakeClock(0.0)) as fake:
        cluster = InMemoryCluster()
        assert cluster.try_acquire_lease("lease", "a", ttl=15.0)
        assert not cluster.try_acquire_lease("lease", "b", ttl=15.0)
        assert cluster.lease_holder("lease") == "a"
        fake.advance(16.0)
        assert cluster.lease_holder("lease") is None
        assert cluster.try_acquire_lease("lease", "b", ttl=15.0)


def test_factories_return_raw_primitives_outside_instrumentation():
    lock = locks.new_lock("x")
    rlock = locks.new_rlock("x")
    cond = locks.new_condition("x")
    assert not isinstance(lock, locks.InstrumentedLock)
    assert not isinstance(rlock, locks.InstrumentedLock)
    assert isinstance(cond, threading.Condition)
    with lock:
        assert lock.locked()
    with rlock, rlock:  # re-entrant
        pass


def test_instrumented_registry_records_order_and_holds():
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        assert isinstance(a, locks.InstrumentedLock)
        with a:
            time.sleep(0.01)
            with b:
                pass
    # built outside the block again
    assert not isinstance(locks.new_lock("c"), locks.InstrumentedLock)

    order = [name for _seq, _thread, name in registry.acquisitions]
    assert order == ["a", "b"]
    assert registry.pair_orders() == {("a", "b")}
    assert registry.inversions() == set()
    (hold,) = registry.hold_times("a")
    assert hold >= 0.01
    assert len(registry.hold_times("b")) == 1


def test_instrumented_registry_detects_inversions():
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        with a:
            with b:
                pass
        # opposite order in another thread (no overlap, so no deadlock —
        # but the ordering conflict is exactly what the registry exists
        # to surface)
        def inverted():
            with b:
                with a:
                    pass

        t = threading.Thread(target=inverted, name="tpujob-test-invert",
                             daemon=True)
        t.start()
        t.join(timeout=5)
    assert registry.inversions() == {("a", "b")}


def test_instrumented_rlock_reentry_is_not_an_inversion():
    with locks.instrumented() as registry:
        r = locks.new_rlock("r")
        with r, r:
            pass
    assert registry.pair_orders() == set()
    assert registry.inversions() == set()


def test_cross_thread_release_does_not_poison_nesting():
    """acquire in A, release in B (legal for raw locks): A's held stack
    must not keep a phantom entry that turns every later acquisition in A
    into a false nesting pair."""
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        assert a.acquire()
        t = threading.Thread(target=a.release, name="tpujob-test-release",
                             daemon=True)
        t.start()
        t.join(timeout=5)
        with b:
            pass
    assert registry.pair_orders() == set()  # no phantom (a, b)
    assert len(registry.hold_times("a")) == 1  # the handoff hold was recorded


def test_inversion_cycles_detects_three_lock_cycle():
    """The pairwise check can NEVER see a 3-way inversion (no pair occurs
    in both orders); full cycle detection must — with the witness cycle."""
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        c = locks.new_lock("c")

        def nest(outer, inner):
            with outer:
                with inner:
                    pass

        for i, (outer, inner) in enumerate([(a, b), (b, c), (c, a)]):
            t = threading.Thread(target=nest, args=(outer, inner),
                                 name=f"tpujob-test-cycle-{i}", daemon=True)
            t.start()
            t.join(timeout=5)
    assert registry.pair_orders() == {("a", "b"), ("b", "c"), ("c", "a")}
    # no pair in both orders — the OLD pairwise definition saw nothing here
    assert not any((y, x) in registry.pair_orders()
                   for x, y in registry.pair_orders())
    assert registry.inversion_cycles() == [["a", "b", "c"]]
    assert registry.inversions() == {("a", "b"), ("b", "c"), ("a", "c")}


def test_inversion_cycles_ignores_edges_outside_the_cycle():
    """An acyclic tail hanging off a 2-cycle must not be reported as part
    of the inversion."""
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        d = locks.new_lock("d")
        with a:
            with b:
                pass
        with b:
            with d:  # acyclic tail
                pass

        def inverted():
            with b:
                with a:
                    pass

        t = threading.Thread(target=inverted, name="tpujob-test-invert",
                             daemon=True)
        t.start()
        t.join(timeout=5)
    assert registry.inversion_cycles() == [["a", "b"]]
    assert registry.inversions() == {("a", "b")}


def test_inversions_complete_when_one_component_has_two_cycles():
    """a⇄b plus a⇄c collapse into ONE strongly-connected component; the
    edge-level inversions() view must still report both pairs (the old
    pairwise behavior), not just the component's single witness cycle."""
    with locks.instrumented() as registry:
        a = locks.new_lock("a")
        b = locks.new_lock("b")
        c = locks.new_lock("c")

        def nest(outer, inner):
            with outer:
                with inner:
                    pass

        for i, (outer, inner) in enumerate(
                [(a, b), (b, a), (a, c), (c, a)]):
            t = threading.Thread(target=nest, args=(outer, inner),
                                 name=f"tpujob-test-two-{i}", daemon=True)
            t.start()
            t.join(timeout=5)
    assert registry.inversions() == {("a", "b"), ("a", "c")}
    assert len(registry.inversion_cycles()) == 1  # one witness per SCC


def test_instrumented_locked_works_for_rlock_too():
    """_thread.RLock has no .locked() before Python 3.14; the wrapper must
    still honor the protocol it advertises."""
    with locks.instrumented():
        lock = locks.new_lock("l")
        rlock = locks.new_rlock("r")
    for lk in (lock, rlock):
        assert not lk.locked()
        with lk:
            assert lk.locked()
        assert not lk.locked()
