"""Client-side QPS/Burst throttling + startup CRD check.

The reference rate-limits its apiserver client (--qps/--burst,
cmd/tf-operator.v1/app/server.go:102-109, app/options/options.go:81-82)
and fails fast at startup when the TFJob CRD is absent (checkCRDExists,
server.go:215-227).  These tests pin the TokenBucket math with a fake
clock, the wire behavior against the strict fixture, both CRD-check
branches, and that a throttled controller still converges a 100-job soak.
"""
import threading
import time

import pytest

from strict_apiserver import StrictApiServer
from testutil import FakeClock, new_tpujob, start_kubelet_sim

from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime.k8s import (
    CRDNotInstalledError,
    KubeClient,
    KubeConfig,
    KubernetesCluster,
    TokenBucket,
)
from tf_operator_tpu.runtime.reconciler import ReconcilerConfig


def make_bucket(qps, burst):
    fc = FakeClock()
    return TokenBucket(qps, burst, clock=fc.clock, sleep=fc.sleep), fc


class TestTokenBucket:
    def test_burst_then_block(self):
        bucket, fc = make_bucket(qps=10, burst=3)
        assert [bucket.acquire() for _ in range(3)] == [0.0, 0.0, 0.0]
        waited = bucket.acquire()  # 4th must wait one refill: 1/qps
        assert waited == pytest.approx(0.1)
        assert fc.slept == [pytest.approx(0.1)]
        assert bucket.wait_count == 1
        assert bucket.wait_seconds == pytest.approx(0.1)

    def test_refill_rate_is_qps(self):
        bucket, fc = make_bucket(qps=5, burst=1)
        bucket.acquire()
        for _ in range(4):
            assert bucket.acquire() == pytest.approx(0.2)  # 1/5 s each

    def test_tokens_cap_at_burst(self):
        bucket, fc = make_bucket(qps=100, burst=2)
        fc.now += 60.0  # a long idle must not bank more than `burst`
        assert bucket.acquire() == 0.0
        assert bucket.acquire() == 0.0
        assert bucket.acquire() == pytest.approx(0.01)

    def test_qps_zero_disables(self):
        bucket, fc = make_bucket(qps=0, burst=1)
        for _ in range(100):
            assert bucket.acquire() == 0.0
        assert fc.slept == []

    def test_thread_safety_conserves_tokens(self):
        # real clock, tiny waits: N threads through a small bucket must
        # each get exactly one token per acquire (no over-issue).
        bucket = TokenBucket(qps=1000, burst=5)
        done = []

        def worker():
            bucket.acquire()
            done.append(1)

        threads = [threading.Thread(target=worker) for _ in range(25)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        took = time.perf_counter() - t0
        assert len(done) == 25
        # 25 acquires, 5 banked: >= ~20ms of refill time must have passed
        assert took >= 0.015


@pytest.fixture
def strict():
    server = StrictApiServer()
    url = server.start()
    yield server, url
    server.stop()


class TestWireThrottle:
    def test_requests_throttled_over_the_wire(self, strict):
        server, url = strict
        client = KubeClient(KubeConfig(host=url, namespace="default"),
                            qps=50, burst=2)
        t0 = time.perf_counter()
        for _ in range(6):
            client.request("GET", "/api/v1/namespaces/default/pods")
        took = time.perf_counter() - t0
        # 6 requests, 2 banked -> >= 4 refills at 20ms each
        assert took >= 0.06
        assert client.limiter.wait_count >= 3
        assert client.limiter.wait_seconds > 0
        # throttling is observable on /metrics (client-go parity)
        from tf_operator_tpu.utils import metrics

        assert metrics.client_throttle_waits.labels().get() >= 3
        assert metrics.client_throttle_wait_seconds.labels().get() > 0
        rendered = metrics.REGISTRY.render()
        assert "tpu_operator_client_throttle_waits_total" in rendered

    def test_server_flags_exist_with_reference_defaults(self):
        from tf_operator_tpu.server.server import build_arg_parser

        parser = build_arg_parser()
        args = parser.parse_args([])
        assert args.qps == 5.0 and args.burst == 10  # ref options.go:81-82
        assert args.resync_period == 15.0
        # the reference's typo'd spelling (options.go:79) is accepted so
        # its Deployment args run unmodified
        assert parser.parse_args(
            ["--resyc-period", "30"]).resync_period == 30.0

    def test_cluster_passes_qps_to_client(self, strict):
        _server, url = strict
        cluster = KubernetesCluster(
            KubeConfig(host=url, namespace="default"), namespace="default",
            qps=42, burst=7)
        try:
            assert cluster.client.limiter.qps == 42
            assert cluster.client.limiter.burst == 7
        finally:
            cluster.close()


class TestCRDCheck:
    def test_present_crd_passes(self, strict):
        _server, url = strict
        cluster = KubernetesCluster(
            KubeConfig(host=url, namespace="default"), namespace="default",
            qps=0)
        try:
            cluster.check_crd_exists()  # must not raise
        finally:
            cluster.close()

    def test_missing_crd_raises_actionable_error(self, strict):
        server, url = strict
        server.missing_plurals.add("tpujobs")
        cluster = KubernetesCluster(
            KubeConfig(host=url, namespace="default"), namespace="default",
            qps=0)
        try:
            with pytest.raises(CRDNotInstalledError) as exc:
                cluster.check_crd_exists()
            msg = str(exc.value)
            assert "kubectl apply -f manifests/crd.yaml" in msg
            assert "tpujobs" in msg
        finally:
            cluster.close()

    def test_inconclusive_check_continues_startup(self):
        """Only a confirmed-absent CRD is fatal: a transient 5xx, an RBAC
        403, or a connection failure at startup must log-and-continue
        (the reference's checkCRDExists only treats IsNotFound as fatal),
        not crash-loop the operator (ADVICE r05)."""
        import logging

        from tf_operator_tpu.runtime.k8s import ApiError
        from tf_operator_tpu.server.server import startup_crd_check

        log = logging.getLogger("test-crd-check")

        class Flaky:
            def __init__(self, exc):
                self.exc = exc

            def check_crd_exists(self):
                raise self.exc

        for exc in (ApiError(403, "forbidden"), ApiError(503, "apiserver busy"),
                    ConnectionRefusedError("down")):
            startup_crd_check(Flaky(exc), log)  # must not raise

        with pytest.raises(SystemExit):
            startup_crd_check(Flaky(CRDNotInstalledError("absent")), log)

    def test_server_run_fails_fast_on_missing_crd(self, strict):
        server, url = strict
        server.missing_plurals.add("tpujobs")
        cluster = KubernetesCluster(
            KubeConfig(host=url, namespace="default"), namespace="default",
            qps=0)
        from tf_operator_tpu.server import server as server_mod

        try:
            with pytest.raises(SystemExit) as exc:
                server_mod.run(argv=[], cluster=cluster)
            assert "manifests/crd.yaml" in str(exc.value)
        finally:
            cluster.close()


@pytest.mark.slow
def test_throttled_hundred_job_soak(strict):
    """The conformance-battery soak with the reference-style client
    limiter ON: the controller must still converge 100 jobs, and the
    limiter must demonstrably have engaged (real back-pressure, not a
    no-op).  Polling happens fixture-side so the assertion loop doesn't
    consume the controller's token budget.

    The engage threshold is DERIVED from this machine's measured request
    rate instead of hard-coded: an unthrottled probe measures how fast the
    client can actually reach the fixture, and the soak runs at 1/8 of
    that, so the 100-job submission burst alone must overrun the bucket on
    any host.  (The old hard-coded qps=400 flaked 'limiter never engaged'
    on machines that could not generate 400 req/s in the first place.)"""
    server, url = strict
    probe = KubeClient(KubeConfig(host=url, namespace="default"), qps=0)
    t0 = time.perf_counter()
    probe_requests = 40
    for _ in range(probe_requests):
        probe.request("GET", "/api/v1/namespaces/default/pods")
    measured_rate = probe_requests / max(time.perf_counter() - t0, 1e-6)
    qps = max(10.0, measured_rate / 8.0)
    cluster = KubernetesCluster(
        KubeConfig(host=url, namespace="default"), namespace="default",
        qps=qps, burst=25)
    controller = TPUJobController(
        cluster, config=ReconcilerConfig(reconciler_sync_loop_period=0.25),
        threadiness=4)
    controller.start()
    stop_kubelet = start_kubelet_sim(server)
    n = 100
    try:
        for i in range(n):
            cluster.create_job(new_tpujob(worker=1, name=f"soak-{i:03d}"))

        def all_running():
            jobs = server.objects("tpujobs")
            if len(jobs) != n:
                return False
            running = 0
            for obj in jobs.values():
                for cond in ((obj.get("status") or {}).get("conditions")
                             or []):
                    if (cond.get("type") == "Running"
                            and cond.get("status") == "True"):
                        running += 1
            return running == n

        deadline = time.time() + 180
        while time.time() < deadline and not all_running():
            time.sleep(0.1)
        assert all_running(), "throttled soak did not converge"
        limiter = cluster.client.limiter
        assert limiter.wait_count > 0, (
            f"limiter never engaged (measured_rate={measured_rate:.0f}/s, "
            f"qps={qps:.0f})")
        assert limiter.wait_seconds > 0
    finally:
        stop_kubelet()
        controller.stop()
        cluster.close()
