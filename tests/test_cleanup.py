"""Terminal-state cleanup + gang scheduling tests.

Mirrors /root/reference/pkg/controller.v1/tensorflow/job_test.go:189
(TestDeletePodsAndServices), the CleanPodPolicy E2E suite
(py/kubeflow/tf_operator cleanpod_policy_tests.py semantics), TTL cleanup
(common/job.go:307-330), and PodGroup lifecycle
(common/job_controller.go:211-239).
"""
import time

from tf_operator_tpu.api.core import PodPhase
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    ReplicaType,
)
from tf_operator_tpu.runtime import conditions
from tf_operator_tpu.runtime.cluster import NotFound

from testutil import new_controller, new_pod, new_tpujob


def make_succeeded_job(policy):
    job = new_tpujob(worker=2)
    job.spec.run_policy.clean_pod_policy = policy
    conditions.update_job_conditions(
        job.status, JobConditionType.SUCCEEDED, "TPUJobSucceeded", "done"
    )
    job.status.completion_time = time.time()
    return job


class TestCleanPodPolicy:
    def _run(self, policy):
        controller, cluster, fake_pods, fake_services = new_controller()
        job = make_succeeded_job(policy)
        cluster.create_pod(new_pod(job, ReplicaType.WORKER, 0, PodPhase.SUCCEEDED, exit_code=0))
        cluster.create_pod(new_pod(job, ReplicaType.WORKER, 1, PodPhase.RUNNING))
        cluster.create_job(job)
        controller.sync_job(job.key())
        return fake_pods, fake_services

    def test_running(self):
        # only the running pod deleted (ref: job.go:113-121 + CleanPodPolicy)
        fake_pods, fake_services = self._run(CleanPodPolicy.RUNNING)
        assert fake_pods.deleted_pod_names == ["test-tpujob-worker-1"]
        assert len(fake_services.deleted_service_names) == 0  # none existed

    def test_all(self):
        fake_pods, _ = self._run(CleanPodPolicy.ALL)
        assert sorted(fake_pods.deleted_pod_names) == [
            "test-tpujob-worker-0",
            "test-tpujob-worker-1",
        ]

    def test_none(self):
        fake_pods, _ = self._run(CleanPodPolicy.NONE)
        assert fake_pods.deleted_pod_names == []

    def test_services_deleted_with_pods(self):
        controller, cluster, fake_pods, fake_services = new_controller()
        from tf_operator_tpu.runtime.control import RealPodControl, RealServiceControl

        controller.reconciler.pod_control = RealPodControl(cluster)
        controller.reconciler.service_control = RealServiceControl(cluster)
        job = new_tpujob(worker=2)
        cluster.create_job(job)
        controller.sync_job(job.key())
        assert len(cluster.list_services()) == 2
        # finish the job
        for i in range(2):
            cluster.set_pod_phase("default", f"test-tpujob-worker-{i}", PodPhase.SUCCEEDED, exit_code=0)
        controller.sync_job(job.key())  # marks succeeded
        controller.sync_job(job.key())  # terminal cleanup
        assert cluster.list_services() == []


def test_succeeded_flips_active_to_succeeded():
    """Terminal sync folds active counts into succeeded (ref: job.go:128-136)."""
    controller, cluster, _, _ = new_controller()
    job = make_succeeded_job(CleanPodPolicy.NONE)
    from tf_operator_tpu.api.types import ReplicaStatus

    job.status.replica_statuses = {"Worker": ReplicaStatus(active=2, succeeded=0)}
    cluster.create_job(job)
    controller.sync_job(job.key())
    stored = cluster.get_job("default", "test-tpujob")
    rs = stored.status.replica_statuses["Worker"]
    assert (rs.active, rs.succeeded) == (0, 2)


class TestTTL:
    def test_expired_job_deleted(self):
        controller, cluster, _, _ = new_controller()
        job = make_succeeded_job(CleanPodPolicy.NONE)
        job.spec.run_policy.ttl_seconds_after_finished = 1
        job.status.completion_time = time.time() - 100
        cluster.create_job(job)
        controller.sync_job(job.key())
        try:
            cluster.get_job("default", "test-tpujob")
            assert False, "job should have been TTL-deleted"
        except NotFound:
            pass

    def test_unexpired_job_kept(self):
        controller, cluster, _, _ = new_controller()
        job = make_succeeded_job(CleanPodPolicy.NONE)
        job.spec.run_policy.ttl_seconds_after_finished = 3600
        cluster.create_job(job)
        controller.sync_job(job.key())
        assert cluster.get_job("default", "test-tpujob") is not None

    def test_no_ttl_job_kept(self):
        controller, cluster, _, _ = new_controller()
        job = make_succeeded_job(CleanPodPolicy.NONE)
        cluster.create_job(job)
        controller.sync_job(job.key())
        assert cluster.get_job("default", "test-tpujob") is not None


class TestGangScheduling:
    def test_podgroup_created_with_min_member(self):
        controller, cluster, fake_pods, _ = new_controller(enable_gang=True)
        job = new_tpujob(worker=4, ps=2)
        cluster.create_job(job)
        controller.sync_job(job.key())
        pg = cluster.get_podgroup("default", "test-tpujob")
        assert pg.min_member == 6
        # pods stamped with scheduler name + group annotation
        # (ref: pod.go:218-231)
        from tf_operator_tpu.api import constants

        pod = fake_pods.pods[0]
        assert pod.spec.scheduler_name == constants.GANG_SCHEDULER_NAME
        assert pod.metadata.annotations[constants.GANG_GROUP_ANNOTATION] == "test-tpujob"

    def test_min_available_override(self):
        from tf_operator_tpu.api.types import RunPolicy, SchedulingPolicy

        controller, cluster, _, _ = new_controller(enable_gang=True)
        job = new_tpujob(worker=4)
        job.spec.run_policy.scheduling_policy = SchedulingPolicy(min_available=3)
        cluster.create_job(job)
        controller.sync_job(job.key())
        assert cluster.get_podgroup("default", "test-tpujob").min_member == 3

    def test_podgroup_deleted_on_terminal(self):
        controller, cluster, _, _ = new_controller(enable_gang=True)
        job = make_succeeded_job(CleanPodPolicy.NONE)
        cluster.create_job(job)
        controller.sync_job(job.key())
        try:
            cluster.get_podgroup("default", "test-tpujob")
            assert False, "podgroup should be deleted on terminal job"
        except NotFound:
            pass

    def test_no_gang_no_podgroup(self):
        controller, cluster, _, _ = new_controller(enable_gang=False)
        job = new_tpujob(worker=2)
        cluster.create_job(job)
        controller.sync_job(job.key())
        try:
            cluster.get_podgroup("default", "test-tpujob")
            assert False
        except NotFound:
            pass
