"""In-memory end-to-end: controller thread + watch events + real controls.

The hermetic analogue of the reference's E2E flow (simple_tfjob_tests.py:26-87):
submit job → pods/services appear → phases flow → conditions transition →
terminal cleanup. No real processes; pod phases are driven by the test like
the kubelet would.
"""
import time

import pytest

from tf_operator_tpu.api.core import PodPhase
from tf_operator_tpu.api.types import JobConditionType, ReplicaType
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime import conditions
from tf_operator_tpu.runtime.cluster import InMemoryCluster
from tf_operator_tpu.utils import locks

from testutil import new_tpujob


def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def running_controller():
    cluster = InMemoryCluster()
    controller = TPUJobController(cluster, threadiness=2)
    controller.start()
    yield cluster, controller
    controller.stop()


def drive_to_succeeded(cluster, expect_pods):
    """The kubelet side of a happy-path run: wait for the reconcile loop's
    pods, take everything to Running, finish the workers, wait for
    Succeeded (worker-0 rule covers any remaining PS)."""
    assert wait_for(lambda: len(cluster.list_pods()) == expect_pods), "pods not created"
    for pod in cluster.list_pods():
        cluster.set_pod_phase(pod.metadata.namespace, pod.metadata.name, PodPhase.RUNNING)
    assert wait_for(
        lambda: conditions.is_running(cluster.get_job("default", "test-tpujob").status)
    ), "job did not reach Running"
    for pod in cluster.list_pods(selector={"replica-type": "worker"}):
        cluster.set_pod_phase(pod.metadata.namespace, pod.metadata.name,
                              PodPhase.SUCCEEDED, exit_code=0)
    assert wait_for(
        lambda: conditions.is_succeeded(cluster.get_job("default", "test-tpujob").status)
    ), "job did not reach Succeeded"


def test_full_lifecycle(running_controller):
    cluster, controller = running_controller
    job = new_tpujob(worker=2, ps=1)
    cluster.create_job(job)

    assert wait_for(lambda: len(cluster.list_services()) == 3), "services not created"
    drive_to_succeeded(cluster, expect_pods=3)

    # terminal cleanup: running PS pod deleted under default CleanPodPolicy
    assert wait_for(
        lambda: all(
            p.status.phase != PodPhase.RUNNING for p in cluster.list_pods()
        )
    ), "running pods not cleaned up"


def test_failure_lifecycle(running_controller):
    cluster, controller = running_controller
    job = new_tpujob(worker=2)
    cluster.create_job(job)
    assert wait_for(lambda: len(cluster.list_pods()) == 2)
    pods = cluster.list_pods()
    cluster.set_pod_phase("default", pods[0].metadata.name, PodPhase.RUNNING)
    cluster.set_pod_phase("default", pods[1].metadata.name, PodPhase.FAILED, exit_code=1)
    assert wait_for(
        lambda: conditions.is_failed(cluster.get_job("default", "test-tpujob").status)
    ), "job did not fail"


def test_exit_code_restart_lifecycle(running_controller):
    from tf_operator_tpu.api.types import RestartPolicy

    cluster, controller = running_controller
    job = new_tpujob(worker=2, restart_policy=RestartPolicy.EXIT_CODE)
    cluster.create_job(job)
    assert wait_for(lambda: len(cluster.list_pods()) == 2)
    # preemption-style SIGKILL on worker 0
    cluster.set_pod_phase("default", "test-tpujob-worker-0", PodPhase.FAILED, exit_code=137)
    # pod deleted and recreated fresh (Pending)
    assert wait_for(
        lambda: any(
            p.metadata.name == "test-tpujob-worker-0"
            and p.status.phase == PodPhase.PENDING
            for p in cluster.list_pods()
        )
    ), "worker-0 was not restarted"
    stored = cluster.get_job("default", "test-tpujob")
    assert conditions.has_condition(stored.status, JobConditionType.RESTARTING)


@pytest.fixture
def instrumented_controller():
    """Opt-in (deliberately NOT autouse — the wrappers add a Python frame
    to every acquire, which the tier-1 budget does not want on every test):
    builds cluster + controller inside `locks.instrumented()` so every lock
    the control plane constructs reports acquisition order and hold times
    to the registry."""
    with locks.instrumented() as registry:
        cluster = InMemoryCluster()
        controller = TPUJobController(cluster, threadiness=2)
    controller.start()
    yield cluster, controller, registry
    controller.stop()


def test_lock_acquisition_order_is_consistent(instrumented_controller):
    """Full job lifecycle under instrumented locks: the control plane must
    exhibit a globally consistent lock order — no thread taking A then B
    while another takes B then A (the deadlock precondition)."""
    cluster, controller, registry = instrumented_controller
    job = new_tpujob(worker=2, ps=1)
    cluster.create_job(job)
    drive_to_succeeded(cluster, expect_pods=3)

    acquisitions = registry.acquisitions
    assert acquisitions, "instrumentation never engaged"
    names = {name for _seq, _thread, name in acquisitions}
    # the run exercised the substrate and controller seams, not just one lock
    assert "cluster" in names
    assert "expectations" in names
    inversions = registry.inversions()
    assert not inversions, (
        f"inconsistent lock acquisition order (A→B and B→A): {inversions}; "
        f"nestings seen: {sorted(registry.pair_orders())}"
    )
