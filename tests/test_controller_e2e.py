"""In-memory end-to-end: controller thread + watch events + real controls.

The hermetic analogue of the reference's E2E flow (simple_tfjob_tests.py:26-87):
submit job → pods/services appear → phases flow → conditions transition →
terminal cleanup. No real processes; pod phases are driven by the test like
the kubelet would.
"""
import time

import pytest

from tf_operator_tpu.api.core import PodPhase
from tf_operator_tpu.api.types import JobConditionType, ReplicaType
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime import conditions
from tf_operator_tpu.runtime.cluster import InMemoryCluster

from testutil import new_tpujob


def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def running_controller():
    cluster = InMemoryCluster()
    controller = TPUJobController(cluster, threadiness=2)
    controller.start()
    yield cluster, controller
    controller.stop()


def test_full_lifecycle(running_controller):
    cluster, controller = running_controller
    job = new_tpujob(worker=2, ps=1)
    cluster.create_job(job)

    # pods + services created by the reconcile loop
    assert wait_for(lambda: len(cluster.list_pods()) == 3), "pods not created"
    assert wait_for(lambda: len(cluster.list_services()) == 3), "services not created"

    # drive to Running
    for pod in cluster.list_pods():
        cluster.set_pod_phase(pod.metadata.namespace, pod.metadata.name, PodPhase.RUNNING)
    assert wait_for(
        lambda: conditions.is_running(cluster.get_job("default", "test-tpujob").status)
    ), "job did not reach Running"

    # workers finish → job Succeeded (worker-0 rule covers remaining PS)
    for pod in cluster.list_pods(selector={"replica-type": "worker"}):
        cluster.set_pod_phase(pod.metadata.namespace, pod.metadata.name,
                              PodPhase.SUCCEEDED, exit_code=0)
    assert wait_for(
        lambda: conditions.is_succeeded(cluster.get_job("default", "test-tpujob").status)
    ), "job did not reach Succeeded"

    # terminal cleanup: running PS pod deleted under default CleanPodPolicy
    assert wait_for(
        lambda: all(
            p.status.phase != PodPhase.RUNNING for p in cluster.list_pods()
        )
    ), "running pods not cleaned up"


def test_failure_lifecycle(running_controller):
    cluster, controller = running_controller
    job = new_tpujob(worker=2)
    cluster.create_job(job)
    assert wait_for(lambda: len(cluster.list_pods()) == 2)
    pods = cluster.list_pods()
    cluster.set_pod_phase("default", pods[0].metadata.name, PodPhase.RUNNING)
    cluster.set_pod_phase("default", pods[1].metadata.name, PodPhase.FAILED, exit_code=1)
    assert wait_for(
        lambda: conditions.is_failed(cluster.get_job("default", "test-tpujob").status)
    ), "job did not fail"


def test_exit_code_restart_lifecycle(running_controller):
    from tf_operator_tpu.api.types import RestartPolicy

    cluster, controller = running_controller
    job = new_tpujob(worker=2, restart_policy=RestartPolicy.EXIT_CODE)
    cluster.create_job(job)
    assert wait_for(lambda: len(cluster.list_pods()) == 2)
    # preemption-style SIGKILL on worker 0
    cluster.set_pod_phase("default", "test-tpujob-worker-0", PodPhase.FAILED, exit_code=137)
    # pod deleted and recreated fresh (Pending)
    assert wait_for(
        lambda: any(
            p.metadata.name == "test-tpujob-worker-0"
            and p.status.phase == PodPhase.PENDING
            for p in cluster.list_pods()
        )
    ), "worker-0 was not restarted"
    stored = cluster.get_job("default", "test-tpujob")
    assert conditions.has_condition(stored.status, JobConditionType.RESTARTING)
