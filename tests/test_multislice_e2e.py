"""Multislice (MEGASCALE/DCN) E2E: real processes consume the emitted
document (VERDICT round-1 item #5 — previously asserted only at env-var
level; here a 4-process worker group spanning 2 virtual slices forms a live
jax.distributed group and verifies slice ids/coordinator by behavior).
"""
import sys
from pathlib import Path

import pytest

from tf_operator_tpu.api.core import Container, ObjectMeta, PodTemplateSpec
from tf_operator_tpu.api.types import (
    ReplicaSpec,
    ReplicaType,
    TPUJob,
    TPUJobSpec,
    TPUTopology,
)
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime.local import LocalProcessCluster
from tf_operator_tpu.sdk.client import TPUJobClient


@pytest.mark.slow
def test_multislice_document_consumed_by_real_processes(tmp_path):
    """Worker group of 4 with a 2-host slice topology -> 2 virtual slices
    over DCN.  Every process jax.distributed.initializes from the injected
    env, allgathers its slice id over the live group, and checks the fabric
    view (workloads/multislice_check.py).  A wrong slice-id/coordinator
    layout fails the job."""
    repo_root = str(Path(__file__).resolve().parent.parent)
    cluster = LocalProcessCluster(
        workdir=str(tmp_path / "work"),
        extra_env={"TPUJOB_FORCE_PLATFORM": "cpu", "PYTHONPATH": repo_root},
    )
    controller = TPUJobController(cluster, threadiness=2,
                                  resolver=cluster.resolver)
    controller.start()
    client = TPUJobClient(cluster)
    try:
        # AllWorkers: the job succeeds only when every replica's fabric
        # check passed.  With the default worker-0 rule, worker-0 finishing
        # flips the job Succeeded and CleanPodPolicy=Running deletes the
        # still-running peers before they log their OK (a real race this
        # test hit under load — correct operator behavior, wrong policy
        # for an all-replicas assertion).
        from tf_operator_tpu.api.types import SuccessPolicy

        job = TPUJob(
            metadata=ObjectMeta(name="mslice"),
            spec=TPUJobSpec(
                success_policy=SuccessPolicy.ALL_WORKERS,
                replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=4,
                    # v5litepod-8 / 2x4 = 8 chips over 2 hosts -> 4 replicas
                    # span ceil(4/2) = 2 slices
                    tpu=TPUTopology(accelerator="v5litepod-8", topology="2x4"),
                    template=PodTemplateSpec(containers=[Container(
                        name="tensorflow", image="local",
                        command=[sys.executable, "-m",
                                 "tf_operator_tpu.workloads.multislice_check"],
                    )]),
                )
            }),
        )
        client.create(job)
        client.wait_for_job("mslice", timeout=180)
        assert client.is_job_succeeded("mslice")
        # all four succeeded (AllWorkers), but the last log line may still
        # be flushing — poll briefly for every replica's OK marker
        import time as _time

        deadline = _time.time() + 30
        while True:
            logs = client.get_logs("mslice")
            ok = [n for n, t in logs.items() if "multislice_check OK" in t]
            if len(ok) == 4 or _time.time() > deadline:
                break
            _time.sleep(0.2)
        assert len(ok) == 4, logs
    finally:
        controller.stop()
        cluster.close()
