"""Scheduling-policy chaos: mixed-priority load + faults + replica kill.

The ISSUE 20 acceptance scenario at tier-1 scale: a 2-replica controller
fleet drives a mixed load — preemptible low/batch gangs from two tenants
saturating the chip pool, plus pool-scale high-class gangs that must
preempt their way in — through a seeded fault schedule at the
ClusterInterface boundary, with one controller replica crash-killed
mid-soak (no lease release, no graceful handoff).

Invariants sampled THROUGHOUT the soak and asserted at drain:
  - pool accounting is exact: pool.used equals the sum of admitted
    reservations (zero leaked chips), sampled under the scheduler lock;
  - every live bound pod belongs to an admitted gang (zero doubly-admitted
    or half-bound gangs);
  - strict priority: each high-class gang reaches fully-Running while
    lower-class gangs hold or want the pool (the preemption counter must
    engage — capacity is saturated by design);
  - every preempted job requeues — carries the Preempted condition, never
    Failed — and completes once the high-class gangs release the pool;
  - zero lost gangs: every job ends Succeeded.

Failure messages embed the seed; the fault trace replays exactly
(docs/fault-injection.md).
"""
import threading
import time

import pytest

from testutil import new_tpujob

from tf_operator_tpu.api.core import PodPhase
from tf_operator_tpu.api.types import (
    JobConditionType,
    ReplicaType,
    RestartPolicy,
    SchedulingSpec,
    TPUTopology,
)
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime import conditions
from tf_operator_tpu.runtime.cluster import InMemoryCluster, NotFound
from tf_operator_tpu.runtime.faults import FaultInjector, FaultPlan, FaultyCluster
from tf_operator_tpu.runtime.reconciler import ReconcilerConfig
from tf_operator_tpu.runtime.scheduler import GangScheduler
from tf_operator_tpu.runtime.shardlease import ShardLeaseConfig
from tf_operator_tpu.utils import metrics

pytestmark = pytest.mark.chaos

SEED = 20260807
TOTAL_CHIPS = 32  # 4 x 8-chip workers: one big gang == the whole pool
SHORT_JOBS = 12
BIG_GANGS = 2


def wait_for(predicate, timeout=60.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def short_job(i):
    """One preemptible 8-chip worker, low/batch class, tenant a/b mix."""
    job = new_tpujob(worker=1, name=f"short-{i:02d}",
                     restart_policy=RestartPolicy.EXIT_CODE)
    job.spec.replica_specs[ReplicaType.WORKER].tpu = TPUTopology(
        accelerator="v5litepod", topology="2x4")
    job.spec.scheduling = SchedulingSpec(
        priority_class=("low", "batch")[i % 2],
        tenant=("ten-a", "ten-b")[i % 2],
        preemptible=True,
    )
    return job


def big_job(i):
    """A pool-scale high-class gang: admission requires preemption while
    the shorts saturate the pool."""
    job = new_tpujob(worker=4, name=f"big-{i}",
                     restart_policy=RestartPolicy.EXIT_CODE)
    job.spec.replica_specs[ReplicaType.WORKER].tpu = TPUTopology(
        accelerator="v5litepod", topology="2x4")
    job.spec.scheduling = SchedulingSpec(priority_class="high")
    return job


def start_running_kubelet(inner, interval=0.02):
    """Promote Pending pods to Running and leave them there — the soak
    controls completion explicitly so the pool stays saturated."""
    stop_event = threading.Event()

    def loop():
        while not stop_event.is_set():
            for pod in inner.list_pods():
                try:
                    if pod.status.phase == PodPhase.PENDING:
                        inner.set_pod_phase("default", pod.metadata.name,
                                            PodPhase.RUNNING)
                except Exception:  # deleted between snapshot and write
                    continue
            stop_event.wait(interval)

    thread = threading.Thread(target=loop, daemon=True,
                              name="sched-policy-kubelet")
    thread.start()

    def stop():
        stop_event.set()
        thread.join(timeout=5)

    return stop


def complete(inner, name):
    """Succeed every live pod of `name` (releases its reservation)."""
    for pod in inner.list_pods(selector={"job-name": name}):
        if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            continue
        try:
            inner.set_pod_phase("default", pod.metadata.name,
                                PodPhase.SUCCEEDED, exit_code=0)
        except NotFound:
            continue


def fully_running(inner, name, workers):
    pods = [p for p in inner.list_pods(selector={"job-name": name})
            if p.status.phase == PodPhase.RUNNING
            and p.metadata.annotations.get("tpu-operator.dev/bound") == "true"]
    return len(pods) == workers


class SoakProbe:
    """Invariant sampler run inside every wait loop."""

    def __init__(self, inner, scheduler, ctx):
        self.inner = inner
        self.scheduler = scheduler
        self.ctx = ctx
        self.preempted_ever = set()

    def __call__(self):
        from tf_operator_tpu.api import constants

        with self.scheduler._lock:
            admitted = dict(self.scheduler._admitted)
            used = self.scheduler.pool.used
        assert used == sum(admitted.values()), (
            f"leaked pool chips: used={used} admitted={admitted} {self.ctx}")
        assert used <= TOTAL_CHIPS, (admitted, self.ctx)
        for pod in self.inner.list_pods():
            if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
                continue
            if pod.metadata.annotations.get("tpu-operator.dev/bound") != "true":
                continue
            group = pod.metadata.annotations.get(
                constants.GANG_GROUP_ANNOTATION)
            assert f"default/{group}" in admitted, (
                f"bound pod {pod.metadata.name} of non-admitted gang "
                f"{group} {self.ctx}")
        for job in self.inner.list_jobs():
            if conditions.has_condition(job.status,
                                        JobConditionType.PREEMPTED):
                self.preempted_ever.add(job.metadata.name)
            if job.metadata.name in self.preempted_ever:
                assert not conditions.is_failed(job.status), (
                    f"preempted job {job.metadata.name} Failed — preemption "
                    f"must requeue, never Fail {self.ctx}")


def test_mixed_load_soak_with_replica_kill():
    injector = FaultInjector(FaultPlan(seed=SEED, rate=0.15,
                                       latency_range=(0.0, 0.005)))
    inner = InMemoryCluster()
    faulty = FaultyCluster(inner, injector)
    ctx = f"(seed={SEED})"
    preemptions_before = sum(
        metrics.preemptions.value(c) for c in ("low", "batch"))

    # Shared scheduler on the raw substrate; the fleet reconciles through
    # the faulted boundary.  A shared scheduler must not be gated on any
    # single replica's shard split, so ownership is preset wide open —
    # the controller's gang_scheduler setter is first-adopter-only and
    # leaves an explicitly configured gate alone.
    scheduler = GangScheduler(
        inner, total_chips=TOTAL_CHIPS,
        tenant_weights={"ten-a": 2.0, "ten-b": 1.0})
    scheduler.owns_gang = lambda key: True
    fleet = [
        TPUJobController(
            faulty,
            config=ReconcilerConfig(enable_gang_scheduling=True,
                                    reconciler_sync_loop_period=0.1),
            threadiness=1,
            shards=4,
            shard_lease=ShardLeaseConfig(lease_duration=0.8,
                                         renew_period=0.1),
            identity=f"replica-{i}",
        )
        for i in range(2)
    ]
    for c in fleet:
        c.gang_scheduler = scheduler
    probe = SoakProbe(inner, scheduler, ctx)

    def settled(pred):
        def check():
            probe()
            return pred()
        return check

    for c in fleet:
        c.start()
    stop_kubelet = start_running_kubelet(inner)
    try:
        # Phase 1: shorts saturate the pool; the surplus queues.
        for i in range(SHORT_JOBS // 2):
            inner.create_job(short_job(i))
        assert wait_for(settled(
            lambda: scheduler.pool.used == TOTAL_CHIPS), timeout=60), (
            f"shorts never saturated the pool {ctx}\n{injector.describe()}")

        # Phase 2: a high-class pool-scale gang arrives — strict priority
        # demands it preempt its way to fully-Running.
        inner.create_job(big_job(0))
        assert wait_for(settled(
            lambda: fully_running(inner, "big-0", 4)), timeout=60), (
            f"big-0 never preempted its way in {ctx}\n{injector.describe()}")
        preemptions_now = sum(
            metrics.preemptions.value(c) for c in ("low", "batch"))
        assert preemptions_now > preemptions_before, (
            f"pool was saturated yet nothing was preempted {ctx}")

        # Phase 3: mid-soak crash-kill one replica (no lease release) while
        # more load lands.
        victim = fleet[0]
        victim.shard_manager.stop(release=False)
        victim.stop()
        survivor = fleet[1]
        for i in range(SHORT_JOBS // 2, SHORT_JOBS):
            inner.create_job(short_job(i))

        # Phase 4: big-0 completes; the next high gang repeats the cycle
        # against the surviving replica.
        complete(inner, "big-0")
        assert wait_for(settled(
            lambda: scheduler.pool.used == TOTAL_CHIPS), timeout=60), (
            f"requeued shorts never re-admitted {ctx}\n{injector.describe()}")
        inner.create_job(big_job(1))
        assert wait_for(settled(
            lambda: fully_running(inner, "big-1", 4)), timeout=60), (
            f"big-1 never admitted after the replica kill {ctx}\n"
            f"{injector.describe()}")
        complete(inner, "big-1")

        # Drain: complete shorts in waves as they (re-)admit.
        def all_shorts_done():
            probe()
            done = 0
            for i in range(SHORT_JOBS):
                name = f"short-{i:02d}"
                if conditions.is_succeeded(
                        inner.get_job("default", name).status):
                    done += 1
                    continue
                if fully_running(inner, name, 1):
                    complete(inner, name)
            return done == SHORT_JOBS

        assert wait_for(all_shorts_done, timeout=90), (
            f"lost gang: shorts stuck "
            f"{[i for i in range(SHORT_JOBS) if not conditions.is_succeeded(inner.get_job('default', f'short-{i:02d}').status)]} "
            f"{ctx}\n{injector.describe()}")

        # Quiescent end state: nothing admitted, nothing leaked, every
        # gang accounted for, every preempted job requeued and finished.
        assert wait_for(settled(lambda: scheduler.pool.used == 0),
                        timeout=30), f"chips leaked at drain {ctx}"
        with scheduler._lock:
            assert scheduler._admitted == {}, ctx
            assert scheduler._evicting == {}, ctx
        assert probe.preempted_ever, (
            f"soak never observed a Preempted condition {ctx}")
        for job in inner.list_jobs():
            assert conditions.is_succeeded(job.status), (
                f"{job.metadata.name} did not finish {ctx}")
            assert not conditions.is_failed(job.status), ctx
        assert survivor.sync_health.quarantine_count() == 0
        assert injector.trace, "seeded plan injected nothing; rate/seed broken"
    finally:
        stop_kubelet()
        for c in fleet[1:]:
            c.stop()
