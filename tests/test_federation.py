"""Federated controller fleet (runtime/shardlease.py, docs/federation.md).

The contract under test, end to end:

  - N replicas sharing one cluster split the shard space via per-shard
    leases: every shard owned by exactly one replica at all times (no
    doubly-owned), and after any membership change every shard is owned
    again (no lost).
  - A replica killed mid-soak (crash semantics: leases age out, nothing
    released) has its shards adopted by survivors, and every job still
    converges — zero lost keys, zero quarantines.
  - Status writes are coalesced (runtime/statuswriter.py): multi-transition
    passes merge into one PUT, stale-informer echoes of our own last write
    are suppressed, and an idle resync backstop tick performs ZERO status
    writes.
  - The event-driven resync backstop skips quiescent jobs on intermediate
    ticks and still enqueues everything on the full tick.
  - Server flags: --replicas/--shard-lease-*/--full-resync-every parse; the
    reference's misspelled --resyc-period stays a hidden deprecated alias
    of the canonical --resync-period.

The 1,000-job 3-replica soak (the acceptance-scale version of the fast
chaos test here) runs in the slow tier; the interleaving-explorer pin of
the lease-handoff invariant lives in tests/test_schedule_explorer.py.
"""
import threading
import time

import pytest

from tf_operator_tpu.api.core import PodPhase
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.runtime import conditions
from tf_operator_tpu.runtime.cluster import InMemoryCluster
from tf_operator_tpu.runtime.reconciler import ReconcilerConfig
from tf_operator_tpu.runtime.shardlease import (
    REPLICA_LEASE_PREFIX,
    ShardLeaseConfig,
    ShardLeaseManager,
    shard_lease_name,
)
from tf_operator_tpu.runtime.statuswriter import (
    CoalescingStatusWriter,
    snapshot_status,
)
from tf_operator_tpu.runtime.workqueue import RateLimitingQueue, shard_for
from tf_operator_tpu.server.server import build_arg_parser

from testutil import new_tpujob


def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# ShardLeaseManager unit behavior


def test_solo_manager_owns_every_shard():
    cluster = InMemoryCluster()
    mgr = ShardLeaseManager(cluster, "solo",
                            ShardLeaseConfig(num_shards=4, lease_duration=5.0))
    mgr.tick()
    assert mgr.owned_shards() == [0, 1, 2, 3]
    assert all(mgr.owns(s) for s in range(4))
    mgr.stop()
    # graceful stop released every lease
    assert cluster.list_leases(prefix="tpu-operator-shard-") == {}


def test_deterministic_assignment_is_agreed_by_all_members():
    members = ["a", "b", "c"]
    for shard in range(12):
        owners = {ShardLeaseManager.desired_owner(shard, members)}
        assert len(owners) == 1
    # round-robin over the sorted member list
    assert [ShardLeaseManager.desired_owner(s, members) for s in range(6)] == [
        "a", "b", "c", "a", "b", "c"]


def test_two_managers_split_disjointly_and_rebalance_on_graceful_stop():
    cluster = InMemoryCluster()
    a = ShardLeaseManager(cluster, "a",
                          ShardLeaseConfig(num_shards=4, lease_duration=5.0))
    b = ShardLeaseManager(cluster, "b",
                          ShardLeaseConfig(num_shards=4, lease_duration=5.0))
    a.tick()   # solo: a grabs everything
    b.tick()   # b joins (membership), but a's leases are unexpired
    a.tick()   # a sees b and sheds b's share (releases the leases)
    b.tick()   # b acquires the released shards
    owned_a, owned_b = set(a.owned_shards()), set(b.owned_shards())
    assert not (owned_a & owned_b), (owned_a, owned_b)
    assert owned_a | owned_b == {0, 1, 2, 3}
    # graceful stop releases the shard leases AND the membership lease;
    # the survivor's very next tick adopts everything
    b.stop(release=True)
    a.tick()
    assert set(a.owned_shards()) == {0, 1, 2, 3}
    a.stop()


def test_manager_never_doubly_owns_while_peer_lease_unexpired():
    """A partitioned ex-owner that stops renewing loses owns() before the
    lease can expire under the adopter (the ownership margin)."""
    cluster = InMemoryCluster()
    config = ShardLeaseConfig(num_shards=1, lease_duration=1.0,
                              renew_period=0.1)
    a = ShardLeaseManager(cluster, "a", config)
    a.tick()
    assert a.owns(0)
    # 'a' stops ticking (partition).  Before the lease expires, owns()
    # must flip False — strictly before any peer could acquire.
    assert wait_for(lambda: not a.owns(0),
                    timeout=config.lease_duration + 1.0)
    assert cluster.lease_holder(shard_lease_name(0)) in ("a", None)
    # once the lease really expires, a newcomer acquires cleanly
    b = ShardLeaseManager(cluster, "b", ShardLeaseConfig(
        num_shards=1, lease_duration=1.0, renew_period=0.1))
    assert wait_for(lambda: (b.tick() or b.owns(0)), timeout=3.0)
    assert not a.owns(0)
    b.stop()


def test_reacquire_after_own_lapse_is_an_adoption_not_a_renewal():
    """A renew thread that stalls past the lease loses owns() (workers
    absorb the shard's keys on the fence); when it resumes and re-acquires,
    on_adopt MUST fire again — the absorbed keys need the adoption replay,
    and a silent 'renewal' would strand them until the resync backstop."""
    from tf_operator_tpu.utils import clock

    with clock.use(clock.FakeClock(1000.0)) as fake:
        cluster = InMemoryCluster()
        adoptions = []
        mgr = ShardLeaseManager(
            cluster, "stall",
            ShardLeaseConfig(num_shards=1, lease_duration=10.0),
            on_adopt=adoptions.append)
        mgr.tick()
        assert adoptions == [0] and mgr.owns(0)
        # renew cadence: still held, no new adoption
        fake.advance(2.0)
        mgr.tick()
        assert adoptions == [0]
        # the renew thread stalls past the lease: ownership lapses
        fake.advance(11.0)
        assert not mgr.owns(0)
        # resume: the re-acquire is a full adoption (replay), not a renewal
        mgr.tick()
        assert adoptions == [0, 0], (
            "re-acquire after a lapse must fire on_adopt again")
        assert mgr.owns(0)
        mgr.stop()


def test_lapsed_entry_is_dropped_not_counted_as_held():
    """An entry whose lease lapsed while the shard moved away must be
    removed on the next tick, not linger inflating the held count."""
    from tf_operator_tpu.utils import clock

    with clock.use(clock.FakeClock(1000.0)) as fake:
        cluster = InMemoryCluster()
        mgr = ShardLeaseManager(
            cluster, "zz-late",
            ShardLeaseConfig(num_shards=1, lease_duration=10.0))
        mgr.tick()
        assert mgr.owns(0)
        fake.advance(11.0)  # lapse
        # a peer (sorted first) took over while we were stalled
        peer = ShardLeaseManager(
            cluster, "aa-peer",
            ShardLeaseConfig(num_shards=1, lease_duration=10.0))
        peer.tick()
        assert peer.owns(0)
        mgr.tick()  # not desired anymore AND lapsed: entry must go
        with mgr._lock:
            assert 0 not in mgr._owned
        assert not mgr.owns(0) and peer.owns(0)
        peer.stop()
        mgr.stop()


def test_adopt_and_drop_callbacks_fire_with_owned_set_already_updated():
    cluster = InMemoryCluster()
    seen = []

    mgr = ShardLeaseManager(
        cluster, "cb", ShardLeaseConfig(num_shards=2, lease_duration=5.0),
        on_adopt=lambda s: seen.append(("adopt", s, mgr.owns(s))),
        on_drop=lambda s: seen.append(("drop", s, mgr.owns(s))),
    )
    mgr.tick()
    assert ("adopt", 0, True) in seen and ("adopt", 1, True) in seen
    # a peer appears; cb sheds its share and the drop callback sees the
    # already-updated (False) ownership
    peer = ShardLeaseManager(cluster, "aa",
                             ShardLeaseConfig(num_shards=2, lease_duration=5.0))
    peer.tick()
    mgr.tick()
    drops = [e for e in seen if e[0] == "drop"]
    assert drops and all(owns is False for _, _, owns in drops)
    mgr.stop()
    peer.stop()


# ---------------------------------------------------------------------------
# the coalescing status writer


def _snapshotted(job):
    return snapshot_status(job.status)


def test_writer_suppresses_noop_and_merges_transitions():
    cluster = InMemoryCluster()
    writer = CoalescingStatusWriter(cluster)
    job = new_tpujob(worker=1)
    cluster.create_job(job)

    old = _snapshotted(job)
    # no-op pass: nothing changed, nothing written, nothing counted
    assert writer.write_if_changed(job, old) is False
    assert writer.counters() == {"writes": 0, "coalesced": 0}

    # one pass flips two conditions at once -> ONE write, >=1 coalesced
    from tf_operator_tpu.api.types import JobConditionType

    conditions.update_job_conditions(
        job.status, JobConditionType.CREATED, "TPUJobCreated", "created")
    conditions.update_job_conditions(
        job.status, JobConditionType.RUNNING, "TPUJobRunning", "running")
    assert writer.write_if_changed(job, old) is True
    counts = writer.counters()
    assert counts["writes"] == 1
    assert counts["coalesced"] >= 1, (
        "two transitions merged into one PUT must count as coalesced")


def test_writer_suppresses_stale_read_echo_of_own_last_write():
    """The informer can serve a status that predates our last PUT; a pass
    that re-derives exactly what we already wrote must not re-send it."""
    cluster = InMemoryCluster()
    writer = CoalescingStatusWriter(cluster)
    job = new_tpujob(worker=1)
    cluster.create_job(job)

    from tf_operator_tpu.api.types import JobConditionType

    stale = _snapshotted(job)  # the pre-write (stale) view
    conditions.update_job_conditions(
        job.status, JobConditionType.RUNNING, "TPUJobRunning", "running")
    assert writer.write_if_changed(job, stale) is True

    # next pass read the STALE status and recomputed the same transition
    puts = []
    orig = cluster.update_job_status
    cluster.update_job_status = lambda *a, **k: puts.append(a) or orig(*a, **k)
    assert writer.write_if_changed(job, stale) is False
    assert puts == [], "stale-read echo must not produce a wire write"
    assert writer.counters()["coalesced"] >= 1

    # forget() drops the memory: the same echo would write again (correct
    # after a shard handoff, where a peer may have changed the wire)
    writer.forget(job.key())
    assert writer.write_if_changed(job, stale) is True


def test_writer_forget_where_drops_only_matching_keys():
    cluster = InMemoryCluster()
    writer = CoalescingStatusWriter(cluster)
    for name in ("alpha", "beta"):
        job = new_tpujob(worker=1, name=name)
        cluster.create_job(job)
        from tf_operator_tpu.api.types import JobConditionType

        old = _snapshotted(job)
        conditions.update_job_conditions(
            job.status, JobConditionType.RUNNING, "TPUJobRunning", "r")
        writer.write_if_changed(job, old)
    writer.forget_where(lambda key: key.endswith("alpha"))
    with writer._lock:
        tracked = set(writer._last)
    assert tracked == {"default/beta"}


# ---------------------------------------------------------------------------
# event-driven resync + zero idle writes


def _kubelet(cluster, stop):
    """Mark every phase-less pod Running (the in-memory kubelet)."""
    while not stop.is_set():
        for pod in cluster.list_pods():
            if pod.status.phase == PodPhase.PENDING:
                cluster.set_pod_phase(pod.metadata.namespace,
                                      pod.metadata.name, PodPhase.RUNNING)
        stop.wait(0.01)


def test_idle_steady_state_pays_zero_status_writes_per_resync_tick():
    cluster = InMemoryCluster()
    controller = TPUJobController(
        cluster, config=ReconcilerConfig(reconciler_sync_loop_period=0.1),
        threadiness=2)
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    controller.start()
    kubelet.start()
    try:
        for i in range(5):
            cluster.create_job(new_tpujob(worker=1, name=f"idle-{i}"))
        assert wait_for(lambda: all(
            conditions.is_running(j.status) for j in cluster.list_jobs()))
        # settle: let in-flight passes finish and quiescence land
        assert wait_for(lambda: len(controller.work_queue) == 0)
        time.sleep(0.3)
        before = controller.status_writer.counters()["writes"]
        time.sleep(1.0)  # ~10 resync ticks, full ticks included
        after = controller.status_writer.counters()["writes"]
        assert after == before, (
            f"{after - before} status writes during idle steady state; "
            "resync backstop ticks must be wire-silent")
        # and the idle jobs are marked quiescent (skipped between full ticks)
        assert all(controller._is_quiescent(j.key())
                   for j in cluster.list_jobs())
    finally:
        stop.set()
        controller.stop()


def test_full_resync_tick_still_enqueues_quiescent_jobs():
    """The backstop half of event-driven sync: quiescence only skips
    INTERMEDIATE ticks; the Nth tick syncs everything again."""
    cluster = InMemoryCluster()
    controller = TPUJobController(
        cluster, config=ReconcilerConfig(reconciler_sync_loop_period=0.05),
        threadiness=1)
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    controller.start()
    kubelet.start()
    try:
        cluster.create_job(new_tpujob(worker=1, name="backstop"))
        assert wait_for(lambda: conditions.is_running(
            cluster.get_job("default", "backstop").status))
        assert wait_for(
            lambda: controller._is_quiescent("default/backstop"))
        delivered_before = controller.work_queue.stats()["delivered"]
        # across >= 2*full_resync_every periods at least one full tick ran
        time.sleep(0.05 * controller.healing.full_resync_every * 2 + 0.2)
        delivered_after = controller.work_queue.stats()["delivered"]
        assert delivered_after > delivered_before, (
            "full resync ticks must still deliver quiescent keys")
    finally:
        stop.set()
        controller.stop()


def test_watch_event_clears_quiescence():
    cluster = InMemoryCluster()
    controller = TPUJobController(cluster, threadiness=1)
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    controller.start()
    kubelet.start()
    try:
        cluster.create_job(new_tpujob(worker=1, name="wake"))
        assert wait_for(lambda: controller._is_quiescent("default/wake"))
        pod = cluster.list_pods()[0]
        cluster.set_pod_phase(pod.metadata.namespace, pod.metadata.name,
                              PodPhase.FAILED, exit_code=1)
        assert wait_for(
            lambda: not controller._is_quiescent("default/wake")
            or conditions.is_failed(
                cluster.get_job("default", "wake").status))
    finally:
        stop.set()
        controller.stop()


# ---------------------------------------------------------------------------
# workqueue purge (shard handoff)


def test_queue_purge_drops_queued_dirty_and_delayed_keys():
    q = RateLimitingQueue(name="purge")
    q.add("ns/a")
    q.add("ns/b")
    q.add_after("ns/c", 60.0)
    q.add_rate_limited("ns/d")
    key = q.get(timeout=1)
    q.add(key)  # dirty while processing: done() would normally redeliver
    dropped = q.purge()
    assert dropped >= 2
    assert len(q) == 0
    assert q.stats()["pending_timers"] == 0
    assert q.num_requeues("ns/d") == 0  # backoff state handed off too
    q.done(key)  # dirty mark was purged: no redelivery
    assert len(q) == 0
    q.shutdown()


# ---------------------------------------------------------------------------
# the fleet: chaos replica-kill (fast, tier-1) and the 1k soak (slow)


FLEET_SHARDS = 6


def _fleet(cluster, n=3, shards=FLEET_SHARDS, lease=0.8, renew=0.1,
           resync=0.2):
    return [
        TPUJobController(
            cluster,
            config=ReconcilerConfig(reconciler_sync_loop_period=resync),
            threadiness=1,
            shards=shards,
            shard_lease=ShardLeaseConfig(lease_duration=lease,
                                         renew_period=renew),
            identity=f"replica-{i}",
        )
        for i in range(n)
    ]


def _owned_sets(fleet):
    return [set(c.shard_manager.owned_shards()) for c in fleet]


def _assert_disjoint(fleet):
    """No shard owned by two replicas.  The per-manager snapshots are taken
    at slightly different instants, so a handoff in flight can LOOK like an
    overlap; an apparent duplicate is re-verified with owns() at one
    instant — real double-ownership persists, snapshot skew does not."""
    owned = _owned_sets(fleet)
    claimed = {}
    for idx, shards in enumerate(owned):
        for shard in shards:
            claimed.setdefault(shard, []).append(idx)
    for shard, holders in claimed.items():
        if len(holders) > 1:
            live = [i for i in holders
                    if fleet[i].shard_manager.owns(shard)]
            assert len(live) <= 1, (
                f"shard {shard} doubly owned by replicas {live}")


@pytest.mark.chaos
def test_replica_kill_mid_soak_shards_adopted_and_jobs_converge():
    """The acceptance chaos scenario at tier-1 scale: a 3-replica fleet
    drives jobs to Running while one replica is crash-killed mid-soak; the
    dead replica's shards are adopted (zero lost, zero doubly-owned — the
    ownership sets are sampled throughout) and every job still reaches
    Running with zero quarantines."""
    n_jobs = 40
    cluster = InMemoryCluster()
    fleet = _fleet(cluster)
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    for c in fleet:
        c.start()
    kubelet.start()
    try:
        # the fleet settled into a full, disjoint split
        assert wait_for(lambda: set().union(*_owned_sets(fleet))
                        == set(range(FLEET_SHARDS)))
        _assert_disjoint(fleet)

        for i in range(n_jobs):
            cluster.create_job(new_tpujob(worker=1, name=f"fed-{i:03d}"))

        # mid-soak crash: no lease release, no graceful handoff
        victim = fleet[0]
        victim_shards = set(victim.shard_manager.owned_shards())
        assert victim_shards, "victim owned nothing; test is vacuous"
        victim.shard_manager.stop(release=False)
        victim.stop()
        survivors = fleet[1:]

        def converged():
            jobs = cluster.list_jobs()
            return len(jobs) == n_jobs and all(
                conditions.is_running(j.status) for j in jobs)

        # sample the invariant WHILE converging: never doubly-owned
        deadline = time.time() + 60
        while time.time() < deadline and not (
                converged()
                and set().union(*_owned_sets(survivors))
                == set(range(FLEET_SHARDS))):
            _assert_disjoint(survivors)
            time.sleep(0.02)

        # no lost shard: the survivors own everything, disjointly
        owned = _owned_sets(survivors)
        assert set().union(*owned) == set(range(FLEET_SHARDS)), owned
        _assert_disjoint(survivors)
        # the victim's shards specifically were adopted
        assert victim_shards <= set().union(*owned)

        # no lost key: every job converged, with zero quarantines anywhere
        assert converged(), (
            f"{sum(1 for j in cluster.list_jobs() if conditions.is_running(j.status))}"
            f"/{n_jobs} Running after replica kill")
        for c in survivors:
            assert c.sync_health.quarantine_count() == 0
        # the handoff is visible in the health report
        report = survivors[0].health_report()
        assert report["federation"]["adoptions"] >= 1
        assert sorted(report["federation"]["owned"]) == sorted(
            survivors[0].shard_manager.owned_shards())
    finally:
        stop.set()
        for c in fleet[1:]:
            c.stop()


@pytest.mark.slow
@pytest.mark.chaos
def test_thousand_job_fleet_soak_with_replica_kill():
    """Acceptance scale: 3 replicas, 1,000 jobs, one replica crash-killed
    mid-soak.  All jobs Running, shards adopted, zero quarantines, zero
    doubly-owned samples, and per-job status writes at or under the PR 6
    budget (~7/job) with coalescing engaged under churn."""
    n_jobs = 1000
    cluster = InMemoryCluster()
    fleet = _fleet(cluster, lease=2.0, renew=0.3, resync=0.5)
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    for c in fleet:
        c.start()
    kubelet.start()
    try:
        assert wait_for(lambda: set().union(*_owned_sets(fleet))
                        == set(range(FLEET_SHARDS)))
        t0 = time.perf_counter()
        for i in range(n_jobs):
            cluster.create_job(new_tpujob(worker=1, name=f"soak-{i:04d}"))
            if i == n_jobs // 2:  # crash one replica mid-submission
                fleet[0].shard_manager.stop(release=False)
                fleet[0].stop()
        survivors = fleet[1:]

        def running_count():
            return sum(1 for j in cluster.list_jobs()
                       if conditions.is_running(j.status))

        deadline = time.time() + 240
        while time.time() < deadline and running_count() < n_jobs:
            _assert_disjoint(survivors)
            time.sleep(0.25)
        wall = time.perf_counter() - t0
        assert running_count() == n_jobs, (
            f"only {running_count()}/{n_jobs} Running after kill")
        owned = _owned_sets(survivors)
        assert set().union(*owned) == set(range(FLEET_SHARDS))
        _assert_disjoint(survivors)
        for c in survivors:
            assert c.sync_health.quarantine_count() == 0

        # wire-cost budget: status writes per job at or under PR 6's ~7
        writes = sum(c.status_writer.counters()["writes"] for c in fleet)
        coalesced = sum(c.status_writer.counters()["coalesced"]
                        for c in fleet)
        assert writes / n_jobs <= 7.0, (
            f"{writes / n_jobs:.2f} status writes/job exceeds the budget")
        assert coalesced > 0, "no coalescing under 1k-job churn"
        print(f"\n1k-job 3-replica soak with kill: {wall:.1f}s, "
              f"{writes / n_jobs:.2f} status writes/job, "
              f"{coalesced} coalesced")
    finally:
        stop.set()
        for c in fleet[1:]:
            c.stop()


def test_unowned_keys_are_not_synced_and_adoption_replays_them():
    """Ownership gating at the enqueue seam: keys on a shard whose lease a
    PEER holds are never synced here; once that peer leaves and the shard
    is adopted, its keys are replayed and converge."""
    from tf_operator_tpu.api import constants

    cluster = InMemoryCluster()
    # "aaa-blocker" sorts first, so with two members it is assigned (and
    # holds the lease on) shard 0; the controller gets shard 1.
    blocker = ShardLeaseManager(
        cluster, "aaa-blocker",
        ShardLeaseConfig(num_shards=2, lease_duration=30.0))
    blocker.tick()
    controller = TPUJobController(
        cluster, threadiness=1, shards=2,
        shard_lease=ShardLeaseConfig(num_shards=2, lease_duration=30.0,
                                     renew_period=0.1),
        identity="zzz-controller")
    controller.start()
    # blocker's second tick sees the controller's membership and sheds
    # shard 1 (releasing its lease); the controller's renew loop adopts it.
    blocker.tick()
    assert wait_for(lambda: controller.shard_manager.owned_shards() == [1])
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    kubelet.start()
    try:
        # one job per shard, found by walking the stable hash
        job_names = {}
        i = 0
        while len(job_names) < 2:
            name = f"gate-{i}"
            job_names.setdefault(shard_for(f"default/{name}", 2), name)
            i += 1
        for name in job_names.values():
            cluster.create_job(new_tpujob(worker=1, name=name))
        owned_name, blocked_name = job_names[1], job_names[0]
        assert wait_for(lambda: conditions.is_running(
            cluster.get_job("default", owned_name).status))
        # shard 0's job is untouched: its owner (the blocker) is not a
        # controller, and this replica must not sync an unowned shard
        assert not cluster.list_pods(
            selector={constants.LABEL_JOB_NAME: blocked_name})
        # the blocker leaves gracefully -> controller adopts shard 0 and
        # replays its keys; the blocked job now converges
        blocker.stop(release=True)
        assert wait_for(lambda: conditions.is_running(
            cluster.get_job("default", blocked_name).status), timeout=20)
    finally:
        stop.set()
        controller.stop()


def test_adoption_admits_never_validated_jobs():
    """A job created while its shard was ownerless was never admitted by
    anyone (no replica ran add_job).  Adoption must run the full admission
    — an INVALID spec gets FailedValidation, not a quarantine spiral; a
    valid one gets its Created condition and converges."""
    cluster = InMemoryCluster()
    # hold every shard lease so jobs land in an ownerless-for-us window
    blocker = ShardLeaseManager(
        cluster, "aaa-hold",
        ShardLeaseConfig(num_shards=1, lease_duration=30.0))
    blocker.tick()
    controller = TPUJobController(
        cluster, threadiness=1, shards=1,
        shard_lease=ShardLeaseConfig(num_shards=1, lease_duration=30.0,
                                     renew_period=0.1),
        identity="zzz-ctl")
    controller.start()
    stop = threading.Event()
    kubelet = threading.Thread(target=_kubelet, args=(cluster, stop),
                               daemon=True)
    kubelet.start()
    try:
        bad = new_tpujob(name="bad-spec")  # no replica specs: invalid
        cluster.create_job(bad)
        good = new_tpujob(worker=1, name="good-spec")
        cluster.create_job(good)
        # neither was admitted: no conditions, no events, no pods
        assert not cluster.get_job("default", "bad-spec").status.conditions
        assert not cluster.get_job("default", "good-spec").status.conditions
        blocker.stop(release=True)  # -> controller adopts + replays
        assert wait_for(lambda: any(
            c.reason == "FailedValidation"
            for c in cluster.get_job("default", "bad-spec").status.conditions))
        assert wait_for(lambda: conditions.is_running(
            cluster.get_job("default", "good-spec").status))
        # the admission verdict was PERSISTED: the wire job carries the
        # Created stamp (adoption admits a private copy — nothing else
        # writes the stamp for a job admitted there, and mutating the
        # informer's cached object in place would diverge cache and wire)
        from tf_operator_tpu.api.types import JobConditionType

        wire = cluster.get_job("default", "good-spec").status.conditions
        assert any(c.type == JobConditionType.CREATED for c in wire), wire
        # the bad job never reached the sync path's quarantine machinery
        assert controller.sync_health.quarantine_count() == 0
    finally:
        stop.set()
        controller.stop()


def test_release_lease_over_the_wire_respects_successor_reacquire():
    """KubernetesCluster.release_lease must not delete a lease a successor
    re-acquired between its GET and DELETE (resourceVersion precondition);
    a normal release (no interleaving write) succeeds."""
    from fake_apiserver import FakeApiServer
    from tf_operator_tpu.runtime.k8s import KubeConfig, KubernetesCluster

    server = FakeApiServer()
    url = server.start()
    cluster = KubernetesCluster(KubeConfig(host=url, namespace="default"),
                                namespace="default", qps=0)
    try:
        assert cluster.try_acquire_lease("tpu-operator-shard-0", "a", 30.0)
        assert cluster.list_leases(prefix="tpu-operator-shard-") == {
            "tpu-operator-shard-0": "a"}
        # normal release
        assert cluster.release_lease("tpu-operator-shard-0", "a") is True
        assert cluster.list_leases(prefix="tpu-operator-shard-") == {}
        # stale release: between A's GET (which still shows holder=a) and
        # its DELETE, a successor re-writes the lease (holder=b, rv bump).
        # The DELETE's resourceVersion precondition must fail and leave
        # b's fresh lease intact.
        import copy

        assert cluster.try_acquire_lease("tpu-operator-shard-0", "a", 30.0)
        orig_request = cluster.client.request

        def racing_request(method, path, **kwargs):
            result = orig_request(method, path, **kwargs)
            if method == "GET" and path.endswith("/leases/tpu-operator-shard-0"):
                with server._lock:
                    obj = copy.deepcopy(server._get(
                        "leases", "default", "tpu-operator-shard-0"))
                    obj["spec"]["holderIdentity"] = "b"
                    server._put("leases", "default",
                                "tpu-operator-shard-0", obj)
            return result

        cluster.client.request = racing_request
        try:
            released = cluster.release_lease("tpu-operator-shard-0", "a")
        finally:
            cluster.client.request = orig_request
        assert released is False
        assert cluster.list_leases(prefix="tpu-operator-shard-") == {
            "tpu-operator-shard-0": "b"}
    finally:
        cluster.close()
        server.stop()


def test_racing_lease_acquires_leave_exactly_one_winner_on_the_wire():
    """Two replicas racing to acquire one EXPIRED shard lease over the wire
    substrate: the loser's resourceVersion-conditional PUT must answer 409
    (not clobber), so try_acquire_lease returns False and only one replica
    ever claims the shard — the no-doubly-owned invariant depends on the
    apiserver enforcing the precondition, and the fake must conform."""
    import copy

    from fake_apiserver import FakeApiServer
    from tf_operator_tpu.runtime.k8s import KubeConfig, KubernetesCluster

    server = FakeApiServer()
    url = server.start()
    cluster = KubernetesCluster(KubeConfig(host=url, namespace="default"),
                                namespace="default", qps=0)
    try:
        # an expired lease held by a dead replica
        assert cluster.try_acquire_lease("tpu-operator-shard-0", "dead", 30.0)
        with server._lock:
            obj = copy.deepcopy(server._get(
                "leases", "default", "tpu-operator-shard-0"))
            obj["spec"]["renewTime"] = "2020-01-01T00:00:00.000000Z"
            server._put("leases", "default", "tpu-operator-shard-0", obj)

        # replica b renews between a's GET and a's PUT (the race window)
        orig_request = cluster.client.request

        def racing_request(method, path, **kwargs):
            result = orig_request(method, path, **kwargs)
            if (method == "GET"
                    and path.endswith("/leases/tpu-operator-shard-0")):
                with server._lock:
                    won = copy.deepcopy(server._get(
                        "leases", "default", "tpu-operator-shard-0"))
                    won["spec"]["holderIdentity"] = "b"
                    won["spec"]["renewTime"] = obj["spec"]["renewTime"]
                    server._put("leases", "default",
                                "tpu-operator-shard-0", won)
            return result

        cluster.client.request = racing_request
        try:
            acquired = cluster.try_acquire_lease(
                "tpu-operator-shard-0", "a", 30.0)
        finally:
            cluster.client.request = orig_request
        assert acquired is False, (
            "stale-rv PUT must 409, not steal the lease b just won")
    finally:
        cluster.close()
        server.stop()


def test_lease_renew_time_parses_both_timestamp_shapes():
    """Fraction-less renewTime (another client's writer) must parse — the
    old naive split('.')[0]+'Z' produced a double-Z string that read as
    expired, silently dropping live peers from membership."""
    from tf_operator_tpu.runtime.k8s import lease_renew_time

    fractional = lease_renew_time({"renewTime": "2026-08-04T12:00:00.000000Z"})
    bare = lease_renew_time({"renewTime": "2026-08-04T12:00:00Z"})
    assert fractional is not None and bare is not None
    assert fractional == bare
    assert lease_renew_time({}) is None
    assert lease_renew_time({"renewTime": ""}) is None
    # The fraction is KEPT, not floored: flooring would make peers compute
    # expiry up to 1s early and eat the shard-lease ownership margin.
    half = lease_renew_time({"renewTime": "2026-08-04T12:00:00.500000Z"})
    assert half == pytest.approx(bare + 0.5)


def test_lease_stamp_keeps_microseconds_and_ceils_duration():
    """The k8s lease writer must round-trip the exact renew instant
    (MicroTime stamp, kept by lease_renew_time) and round a fractional ttl
    UP into the integral leaseDurationSeconds field — truncating either
    makes peers see expiry earlier than the holder's local float claim,
    which is the doubly-owned window the ownership margin exists to
    close."""
    from fake_apiserver import FakeApiServer
    from tf_operator_tpu.runtime.k8s import (
        KubeConfig,
        KubernetesCluster,
        lease_renew_time,
        to_rfc3339_micro,
    )

    # stamp/parse round-trip at microsecond precision
    ts = 1765000000.123456
    assert lease_renew_time(
        {"renewTime": to_rfc3339_micro(ts)}) == pytest.approx(ts, abs=1e-6)

    server = FakeApiServer()
    url = server.start()
    cluster = KubernetesCluster(KubeConfig(host=url, namespace="default"),
                                namespace="default", qps=0)
    try:
        assert cluster.try_acquire_lease("tpu-operator-shard-9", "a", 4.5)
        with server._lock:
            spec = server._get("leases", "default",
                               "tpu-operator-shard-9")["spec"]
        assert spec["leaseDurationSeconds"] == 5  # ceil(4.5), never 4
        # the landed stamp parses back to the exact instant written
        # (format keeps the fraction; no floor anywhere on the path)
        assert "." in spec["renewTime"]
        assert lease_renew_time(spec) is not None
    finally:
        cluster.close()
        server.stop()


class _FlakyLeaseCluster:
    """Delegates to an InMemoryCluster but fails the next N SHARD lease
    acquires (membership heartbeats stay up) — a transient apiserver
    blip as the renew path sees it."""

    def __init__(self, inner):
        self._inner = inner
        self.fail_next_shard_acquires = 0

    def try_acquire_lease(self, name, holder, ttl):
        if (self.fail_next_shard_acquires > 0
                and name.startswith("tpu-operator-shard-")):
            self.fail_next_shard_acquires -= 1
            return False
        return self._inner.try_acquire_lease(name, holder, ttl)

    def __getattr__(self, attr):
        return getattr(self._inner, attr)


def test_transient_renew_failure_rides_out_lease_window_without_drop():
    """One failed renew while OUR store lease is still unexpired must NOT
    drop ownership: no peer can acquire before expiry, and dropping would
    purge the shard queue + force a full adoption replay per wire blip.
    The claim rides to the next tick; a successful renew there is a
    renewal, not an adoption."""
    cluster = _FlakyLeaseCluster(InMemoryCluster())
    events = []
    mgr = ShardLeaseManager(
        cluster, "a",
        ShardLeaseConfig(num_shards=2, lease_duration=60.0),
        on_adopt=lambda s: events.append(("adopt", s)),
        on_drop=lambda s: events.append(("drop", s)),
    )
    mgr.tick()
    assert sorted(mgr.owned_shards()) == [0, 1]
    assert events == [("adopt", 0), ("adopt", 1)]

    cluster.fail_next_shard_acquires = 2
    mgr.tick()  # both renews fail — but the 60s leases are nowhere near expiry
    assert sorted(mgr.owned_shards()) == [0, 1], "blip must not drop shards"
    assert [e for e in events if e[0] == "drop"] == []

    mgr.tick()  # recovery: a plain renewal, not a re-adoption replay
    assert sorted(mgr.owned_shards()) == [0, 1]
    assert [e for e in events if e[0] == "adopt"] == [("adopt", 0),
                                                     ("adopt", 1)]


def test_fleet_health_provider_aggregates_all_replicas():
    """--replicas N: the probe is live/ready only when EVERY replica is;
    a wedged peer must flip it even though the primary is fine, with the
    failure reason prefixed by the offender's identity."""
    from tf_operator_tpu.server.server import fleet_health_provider

    class _Stub:
        def __init__(self, identity, live, ready, reasons=()):
            self.identity = identity
            self._report = {"status": "ok" if ready else "not-ready",
                            "live": live, "ready": ready,
                            "reasons": list(reasons)}

        def health_report(self):
            return dict(self._report)

    healthy = _Stub("r0", live=True, ready=True)
    wedged = _Stub("r1", live=True, ready=False,
                   reasons=["workers: 0/4 alive"])
    report = fleet_health_provider([healthy, wedged])()
    assert report["ready"] is False and report["status"] == "not-ready"
    assert report["live"] is True
    assert report["reasons"] == ["r1: workers: 0/4 alive"]
    assert set(report["replicas"]) == {"r0", "r1"}

    all_ok = fleet_health_provider(
        [healthy, _Stub("r1", live=True, ready=True)])()
    assert all_ok == {**all_ok, "status": "ok", "live": True, "ready": True,
                      "reasons": []}


# ---------------------------------------------------------------------------
# server flags


def test_federation_flags_parse_with_defaults():
    args = build_arg_parser().parse_args([])
    assert args.replicas == 1
    assert args.enable_shard_leases is False
    assert args.shard_lease_duration == 15.0
    assert args.shard_lease_renew == 5.0
    assert args.full_resync_every == 4


def test_federation_flags_parse_explicit_values():
    args = build_arg_parser().parse_args([
        "--replicas", "3", "--shard-lease-duration", "2.5",
        "--shard-lease-renew", "0.5", "--full-resync-every", "8",
        "--enable-shard-leases",
    ])
    assert args.replicas == 3
    assert args.enable_shard_leases is True
    assert args.shard_lease_duration == 2.5
    assert args.shard_lease_renew == 0.5
    assert args.full_resync_every == 8


def test_resync_period_canonical_and_typo_alias():
    parser = build_arg_parser()
    # canonical spelling, advertised in --help
    assert parser.parse_args(["--resync-period", "30"]).resync_period == 30.0
    help_text = parser.format_help()
    assert "--resync-period" in help_text
    assert "--resyc-period" not in help_text, (
        "the deprecated typo must stay hidden from --help")
    # the reference's typo still parses (hidden deprecated alias) and warns
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = _Capture()
    logging.getLogger().addHandler(handler)
    try:
        args = parser.parse_args(["--resyc-period", "45"])
    finally:
        logging.getLogger().removeHandler(handler)
    assert args.resync_period == 45.0
    assert any("deprecated" in m for m in records), (
        "using the typo alias must log a deprecation warning")


def test_shard_leases_and_leader_election_are_mutually_exclusive():
    from tf_operator_tpu.server.server import run

    with pytest.raises(SystemExit):
        run(argv=["--replicas", "2", "--enable-leader-election",
                  "--runtime", "memory", "--api-port", "0",
                  "--monitoring-port", "0"],
            cluster=InMemoryCluster())
