"""Benchmark harness — survives the flaky tunneled-TPU environment.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline semantics (BASELINE.md): the reference publishes no numbers; the
driver target is >= 90% of bare-XLA steps/sec for the same model/batch on
the same chip.  So vs_baseline = framework_steps_per_sec / bare_xla_steps_per_sec,
where the bare-XLA baseline is a hand-written train step with no framework
abstractions (same math, same data).  >= 0.9 passes; ~1.0 means the framework
adds no overhead.  For the LM stage the bare baseline additionally uses the
O(T²) XLA attention in place of the Pallas flash kernel, so LM vs_baseline
>= 1.0 means the framework's own kernel BEATS bare XLA — the round-2 VERDICT
(#3) bar.  The ratio is meaningful on any backend, so when the TPU tunnel is
down (round 1: even `jax.devices()` hung for minutes) the harness falls back
to CPU rather than producing nothing; the chosen platform is recorded.

Stages (each skippable, each recorded in "stages"):
- throughput, for BOTH models (BENCH_MODEL picks the headline): N>=3 timed
  windows after warmup, median + spread reported (VERDICT #6 variance bound).
  LM also reports MFU against the v5e bf16 peak (197 TFLOP/s/chip).
- attention ladder: compiled flash vs XLA attention fwd+bwd wall-time at
  several sequence lengths (the kernel's reason to exist, measured directly).
- control plane, local runtime: submit→all-Running on LocalProcessCluster
  (real subprocesses).
- control plane, k8s wire path (VERDICT #4): the same controller driving
  KubernetesCluster over real HTTP against tests/fake_apiserver.py with a
  kubelet simulator, reporting submit→all-Running and a 100-job soak. The
  kind tier is never run inline (it belongs to CI); its status — tooling
  missing vs. deferred to the CI kind job — is recorded either way.
- native transports (VERDICT #7): C++ PS push/pull and C++ dataloader
  throughput vs their Python counterparts (CPU-only micro-bench).

Resilience design (VERDICT.md round-1 item #1):
- The parent process never imports jax.  All jax work happens in child
  subprocesses with hard wall-clock timeouts, so a wedged backend init can
  never hang the bench.
- Backend probe: a trivial `jax.devices()` + tiny matmul child with
  bounded retries decides TPU vs CPU before any expensive compile starts.
- Batch ladder: on child failure/timeout the batch size steps down
  (128 -> 32 -> 8) so *some* number lands even on a sick chip.
- Structured output always: on total failure the single JSON line carries
  `error` + `stage` instead of a traceback.

Timing methodology (throughput child): on the tunneled TPU platform,
`block_until_ready` does NOT synchronize (measured: 8192^3 matmuls "complete"
in 25us of host time while a device_get after the same chain takes the real
55ms/matmul).  The only reliable sync is a device->host transfer.  So each
measured window is ONE compiled region — the step scanned `lax.scan`-style
over STEPS iterations — ended by fetching scalars that depend on the whole
chain.  This also amortizes the ~ms-scale per-call tunnel dispatch.

Env knobs: BENCH_MODEL (resnet|lm), BENCH_BATCH, BENCH_STEPS, BENCH_IMAGE,
BENCH_SEQ, BENCH_WINDOWS, BENCH_FORCE_CPU=1, BENCH_PROBE_TIMEOUT,
BENCH_CHILD_TIMEOUT, BENCH_SKIP_CONTROL_PLANE=1, BENCH_SKIP_SECOND_MODEL=1,
BENCH_SKIP_ATTENTION=1, BENCH_SKIP_NATIVE=1, BENCH_LM_*, and for the k8s
soak: BENCH_K8S_QPS/BENCH_K8S_BURST (client throttle), BENCH_K8S_SHARDS
(reconcile shards, default 4), BENCH_K8S_SOAK_JOBS (default 100),
BENCH_K8S_SOAK_1K=1 (adds the 1,000-job arm, k8s_soak_1000_jobs_sec +
per-job apiserver request counts — docs/informer-cache.md),
BENCH_K8S_SOAK_10K=1 (adds the 10,000-job FEDERATED-fleet arm:
BENCH_K8S_REPLICAS shard-lease replicas, default 3, emitting
k8s_soak_10000_jobs_sec, per-job status-write cost, and per-replica
queue-latency p99 — docs/federation.md; BENCH_K8S_SOAK_10K_JOBS scales
the job count for smoke runs).  BENCH_ZERO=1 adds the ZeRO weight-update
sharding A/B arm (lm_opt_state_bytes_per_device + zero on/off tokens/sec
at dp>=2; BENCH_ZERO_DEVICES virtual devices on the CPU fallback,
default 4 — docs/zero-sharding.md).  BENCH_ELASTIC=1 adds the elastic
resize arm (time-to-recover for a preemption -> dp/2 restore plus the
goodput the shrunken mesh retains vs kill-and-restart's 0.0;
BENCH_ELASTIC_DEVICES virtual devices on the CPU fallback, default 4 —
docs/elasticity.md).  BENCH_SCHED_POLICY=1 adds the scheduling-policy
soak (thousands of short preemptible gangs from two weighted tenants +
a few pool-scale high-class gangs, with FaultRules and a mid-run
replica kill, emitting p99 submit->all-Running per priority class and
the Jain fairness index — docs/scheduling-policy.md; BENCH_SCHED_JOBS
job count default 2000, BENCH_SCHED_WAVE arrival-wave size default 200,
BENCH_SCHED_BIG high-class gangs default 3, BENCH_SCHED_CHIPS pool
size default 64).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

MODEL = os.environ.get("BENCH_MODEL", "resnet")
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
CHILD_TIMEOUT = float(os.environ.get("BENCH_CHILD_TIMEOUT", "1200"))

# TPU v5e (v5 lite) peak bf16 matmul throughput per chip; the MFU
# denominator.  Only reported when the bench actually ran on the tpu family.
V5E_PEAK_FLOPS = 197e12

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "x = jnp.ones((128, 128));"
    "v = jax.device_get((x @ x).sum());"
    "print('PROBE_OK', d[0].platform, len(d))"
)


# ---------------------------------------------------------------------------
# Parent: orchestration (no jax imports here)
# ---------------------------------------------------------------------------

def _run(cmd, env_extra, timeout):
    """Run a child; return (rc, stdout, stderr_tail). rc=-9 on timeout."""
    env = dict(os.environ)
    env.update(env_extra)
    env.setdefault("PYTHONPATH", REPO)
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        return proc.returncode, proc.stdout, proc.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        return -9, out, f"timeout after {timeout}s"


def _last_json(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except (ValueError, TypeError):
                continue
    return None


def _probe_backend(stages):
    """Decide the platform: 'tpu'-family if the real backend answers, else cpu."""
    if os.environ.get("BENCH_FORCE_CPU"):
        stages.append({"stage": "probe", "note": "BENCH_FORCE_CPU set"})
        return None
    for attempt in range(3):
        t0 = time.time()
        rc, out, err = _run([sys.executable, "-c", _PROBE_SRC], {}, PROBE_TIMEOUT)
        dt = round(time.time() - t0, 1)
        for line in out.splitlines():
            if line.startswith("PROBE_OK"):
                _, platform, n = line.split()
                stages.append({"stage": "probe", "attempt": attempt, "ok": True,
                               "platform": platform, "devices": int(n), "sec": dt})
                if platform == "cpu":
                    # jax came up but only on CPU (libtpu missing/broken):
                    # take the small-shape CPU fallback, not the full-size
                    # TPU configuration on a CPU backend.
                    return None
                return platform
        stages.append({"stage": "probe", "attempt": attempt, "ok": False,
                       "sec": dt, "err": err[-300:]})
        time.sleep(2.0)
    return None


def _backend_alive(stages, tag: str) -> bool:
    """One-shot liveness re-check between TPU stages.  The tunneled backend
    can wedge mid-run (observed: `import jax` itself hangs once the tunnel
    dies), after which every child burns its full timeout learning nothing —
    a dead tunnel must cost one short probe, not 20 minutes per stage."""
    t0 = time.time()
    rc, out, _ = _run([sys.executable, "-c", _PROBE_SRC], {},
                      float(os.environ.get("BENCH_REPROBE_TIMEOUT", "90")))
    alive = any(line.startswith("PROBE_OK") for line in out.splitlines())
    stages.append({"stage": f"reprobe:{tag}", "ok": alive,
                   "sec": round(time.time() - t0, 1)})
    return alive


def _cpu_fallback_env():
    """FIXED small shapes so compile+run stay in budget on CPU — deliberately
    ignoring any TPU-sized BENCH_* the user exported (override with
    BENCH_CPU_BATCH only).  NOTE: JAX_PLATFORMS=cpu env is NOT honored — the
    sandbox's sitecustomize re-prepends the axon platform — so children force
    the platform in-process via TPUJOB_FORCE_PLATFORM."""
    return {
        "TPUJOB_FORCE_PLATFORM": "cpu",
        "BENCH_WINDOWS": "5",  # 5 interleaved fw/bare pairs: tighter median
        "BENCH_IMAGE": "64",
        "BENCH_SEQ": "256",
        "BENCH_STEPS": "6",
        "BENCH_LM_VOCAB": "8192",
        "BENCH_LM_LAYERS": "2",
        "BENCH_LM_HEADS": "4",
        "BENCH_LM_DMODEL": "256",
        "BENCH_LM_DFF": "1024",
    }


def _throughput(platform, stages, model):
    """Run the throughput child for `model`, stepping down the batch ladder
    on failure."""
    defaults = {"resnet": "128", "lm": "8"}
    if platform is not None:
        start = int(os.environ.get("BENCH_BATCH", defaults[model])
                    if model == MODEL else defaults[model])
        # only step DOWN from the starting batch — a larger rung can't
        # succeed where a smaller one failed
        ladder = [start] + [b for b in (32, 8, 2) if b < start]
        base_env = {}
    else:
        ladder = [int(os.environ.get("BENCH_CPU_BATCH", "4"))]
        base_env = _cpu_fallback_env()
    best_partial = None
    for batch in ladder:
        env = dict(base_env, BENCH_BATCH=str(batch), BENCH_MODEL=model)
        t0 = time.time()
        rc, out, err = _run(
            [sys.executable, os.path.abspath(__file__), "--child-throughput"],
            env, CHILD_TIMEOUT,
        )
        dt = round(time.time() - t0, 1)
        parsed = _last_json(out)
        stages.append({"stage": f"throughput:{model}", "batch": batch, "rc": rc,
                       "sec": dt, "ok": parsed is not None,
                       **({} if parsed else {"err": err[-300:]})})
        if parsed is not None:
            parsed["platform"] = platform or "cpu"
            if rc == 0:
                return parsed  # complete result: both arms measured
            # A partial emitted before the child died (timeout OR crash) is
            # a fallback, not an answer — keep stepping the ladder for a
            # complete vs_baseline at a smaller batch.
            parsed["partial_rc"] = rc
            if best_partial is None:
                best_partial = parsed
        if platform is not None and rc == -9 and not _backend_alive(
                stages, f"throughput:{model}"):
            # Timed out AND the backend no longer answers: the rest of the
            # ladder would hang the same way.  Stop here.
            return best_partial
    return best_partial


def _attention_ladder(platform, stages):
    """Compiled flash-vs-XLA fwd+bwd wall time over a seq-length ladder,
    then a shorter grouped-query arm (kv_heads = heads/3) pricing the
    GQA-native kernel path against the widen-in-HBM XLA approach."""
    if os.environ.get("BENCH_SKIP_ATTENTION"):
        return None

    def run_child(tag, extra_env, timeout=CHILD_TIMEOUT):
        env = {} if platform is not None else dict(
            TPUJOB_FORCE_PLATFORM="cpu", BENCH_ATTN_SEQS="256,512")
        # persist autotune results across bench attempts — a flaky-window
        # rerun must not redo a completed block-shape search
        env.setdefault("TPUJOB_AUTOTUNE_CACHE",
                       os.path.join(REPO, "artifacts", "autotune_cache.json"))
        env.update(extra_env)
        t0 = time.time()
        rc, out, err = _run(
            [sys.executable, os.path.abspath(__file__), "--child-attention"],
            env, timeout,
        )
        parsed = _last_json(out)
        stages.append({"stage": tag, "rc": rc,
                       "sec": round(time.time() - t0, 1),
                       "ok": parsed is not None,
                       **({} if parsed else {"err": err[-300:]})})
        if parsed is not None and rc != 0:
            # rows measured before the child died (timeout or crash), but
            # the ladder is truncated — must not read as a complete run
            parsed["partial_rc"] = rc
            parsed["partial"] = "ladder truncated by child exit"
        return parsed

    parsed = run_child("attention", {})
    # GQA arm: fewer rungs so a flaky live window still covers it.
    gqa_env = {"BENCH_ATTN_KV_H": "4"}
    if platform is not None:
        gqa_env["BENCH_ATTN_SEQS"] = os.environ.get(
            "BENCH_ATTN_GQA_SEQS", "1024,4096")
    gqa = run_child("attention:gqa", gqa_env)
    # Sliding-window arm: windowed vs full-causal flash — the banded-grid
    # long-context factor.  On CPU it prices only the fallback masks
    # (default window sized to the short CPU rungs).
    win_env = {"BENCH_ATTN_WINDOW": os.environ.get(
        "BENCH_ATTN_WINDOW_SIZE", "1024" if platform is not None else "128")}
    if platform is not None:
        win_env["BENCH_ATTN_SEQS"] = os.environ.get(
            "BENCH_ATTN_WIN_SEQS", "4096,8192")
    win = run_child("attention:window", win_env)
    # Attach arms to whichever child succeeded: a main-arm failure must not
    # discard arm rows that already spent (scarce) chip time.
    base = parsed if parsed is not None else (gqa if gqa is not None else win)
    if base is not None:
        if gqa is not None and base is not gqa:
            base["gqa_arm"] = gqa
        if win is not None and base is not win:
            base["window_arm"] = win
    return base


def _control_plane(stages):
    """Submit→all-Running on the local-process runtime AND over the k8s wire
    path (fake apiserver + kubelet sim), plus a 100-job k8s soak."""
    if os.environ.get("BENCH_SKIP_CONTROL_PLANE"):
        return None
    result = {}
    for child, key in (("--child-control-plane", "local"),
                       ("--child-k8s-control-plane", "k8s")):
        t0 = time.time()
        rc, out, err = _run(
            [sys.executable, os.path.abspath(__file__), child],
            {"TPUJOB_FORCE_PLATFORM": "cpu"}, 300,
        )
        parsed = _last_json(out)
        ok = parsed is not None and "error" not in (parsed or {})
        entry = {"stage": f"control_plane:{key}", "rc": rc,
                 "sec": round(time.time() - t0, 1), "ok": ok}
        if not ok:
            entry["err"] = (parsed or {}).get("error") or err[-300:]
        stages.append(entry)
        if ok:
            result[key] = parsed
    # kind (real k8s-in-docker) tier: record its status either way — the
    # bench never runs it inline (it belongs to the CI kind job,
    # .github/workflows/ci.yaml), so absence of tooling vs. deferral to CI
    # are reported distinctly.
    missing = [b for b in ("docker", "kind") if shutil.which(b) is None]
    if missing:
        result["kind"] = f"skipped: no {'/'.join(missing)} binary in bench environment"
    else:
        result["kind"] = "not run inline: covered by the CI kind-E2E job"
    return result or None


def _zero_ab(stages, platform):
    """ZeRO weight-update sharding A/B (docs/zero-sharding.md), env-gated
    BENCH_ZERO=1 so smoke runs never pay the extra compiles: zero=on/off
    tokens/sec pair + opt-state bytes/device at dp>=2.  On the CPU fallback
    the child forces BENCH_ZERO_DEVICES virtual devices (default 4) — its
    own process, so the headline arm's device count is untouched."""
    if os.environ.get("BENCH_ZERO") != "1":
        return None
    env = {}
    if platform is None:
        env["TPUJOB_FORCE_PLATFORM"] = "cpu"
        env["BENCH_ZERO_DEVICES"] = os.environ.get("BENCH_ZERO_DEVICES", "4")
    t0 = time.time()
    rc, out, err = _run(
        [sys.executable, os.path.abspath(__file__), "--child-zero"],
        env, CHILD_TIMEOUT,
    )
    parsed = _last_json(out)
    ok = parsed is not None and "error" not in (parsed or {})
    stages.append({"stage": "zero_ab", "rc": rc,
                   "sec": round(time.time() - t0, 1), "ok": ok,
                   **({} if ok else
                      {"err": (parsed or {}).get("error") or err[-300:]})})
    return parsed if ok else None


def _elastic_ab(stages, platform):
    """Elastic resize A/B (docs/elasticity.md), env-gated BENCH_ELASTIC=1:
    time-to-recover (preemption -> dp/2 restore -> first optimizer step,
    checkpoint re-shard and recompile included) and the goodput the shrunken
    mesh retains vs full width.  The kill-and-restart baseline retains 0.0
    while the slice is gone — that constant IS the comparison, no sleep
    theater needed.  On the CPU fallback the child forces
    BENCH_ELASTIC_DEVICES virtual devices (default 4) in its own process."""
    if os.environ.get("BENCH_ELASTIC") != "1":
        return None
    env = {}
    if platform is None:
        env["TPUJOB_FORCE_PLATFORM"] = "cpu"
        env["BENCH_ELASTIC_DEVICES"] = os.environ.get(
            "BENCH_ELASTIC_DEVICES", "4")
    t0 = time.time()
    rc, out, err = _run(
        [sys.executable, os.path.abspath(__file__), "--child-elastic"],
        env, CHILD_TIMEOUT,
    )
    parsed = _last_json(out)
    ok = parsed is not None and "error" not in (parsed or {})
    stages.append({"stage": "elastic_ab", "rc": rc,
                   "sec": round(time.time() - t0, 1), "ok": ok,
                   **({} if ok else
                      {"err": (parsed or {}).get("error") or err[-300:]})})
    return parsed if ok else None


def _sched_policy(stages):
    """Scheduling-policy soak (docs/scheduling-policy.md), env-gated
    BENCH_SCHED_POLICY=1: thousands of short preemptible low/batch gangs
    from two weighted tenants churn through the policy queue while a few
    pool-scale high-class gangs preempt their way in, under injected
    FaultRules and one mid-run controller-replica crash-kill.  Emits p99
    submit->all-Running per priority class and the Jain fairness index of
    the weighted tenant dominant shares.  Pure control plane — no jax."""
    if os.environ.get("BENCH_SCHED_POLICY") != "1":
        return None
    t0 = time.time()
    rc, out, err = _run(
        [sys.executable, os.path.abspath(__file__), "--child-sched-policy"],
        {"TPUJOB_FORCE_PLATFORM": "cpu"}, CHILD_TIMEOUT,
    )
    parsed = _last_json(out)
    ok = parsed is not None and "error" not in (parsed or {})
    stages.append({"stage": "sched_policy", "rc": rc,
                   "sec": round(time.time() - t0, 1), "ok": ok,
                   **({} if ok else
                      {"err": (parsed or {}).get("error") or err[-300:]})})
    return parsed if ok else None


def _native(stages):
    if os.environ.get("BENCH_SKIP_NATIVE"):
        return None
    t0 = time.time()
    rc, out, err = _run(
        [sys.executable, os.path.abspath(__file__), "--child-native"],
        {"TPUJOB_FORCE_PLATFORM": "cpu"}, 300,
    )
    parsed = _last_json(out)
    stages.append({"stage": "native", "rc": rc,
                   "sec": round(time.time() - t0, 1),
                   "ok": parsed is not None,
                   **({} if parsed else {"err": err[-300:]})})
    return parsed


def orchestrate() -> None:
    stages = []
    results = {}
    platform = None
    # Liveness re-checks only run once a TPU stage has actually failed
    # (tpu_suspect) — a stage that just succeeded proves the backend alive,
    # and skipped stages shouldn't pay a probe at all.
    def tpu_dead(tag: str) -> bool:
        return (platform is not None and tpu_suspect
                and not _backend_alive(stages, tag))

    tpu_suspect = False
    attention = None
    attention_done = False

    def _run_attention():
        nonlocal attention, attention_done, tpu_suspect
        attention_done = True
        try:
            if os.environ.get("BENCH_SKIP_ATTENTION"):
                pass
            elif tpu_dead("attention"):
                stages.append({"stage": "attention",
                               "skipped": "backend unreachable"})
            else:
                attention = _attention_ladder(platform, stages)
                if platform is not None:
                    tpu_suspect = (
                        attention is None
                        or bool(attention.get("partial_rc"))
                        or bool((attention.get("gqa_arm") or {})
                                .get("partial_rc")))
        except Exception as e:  # noqa: BLE001
            stages.append({"stage": "attention", "err": repr(e)[:300]})

    try:
        platform = _probe_backend(stages)
        results[MODEL] = _throughput(platform, stages, MODEL)
        tpu_suspect = platform is not None and bool(
            results[MODEL] is None or results[MODEL].get("partial_rc"))
        # On a flaky backend the caller can pull the flash-vs-XLA ladder
        # ahead of the second model (BENCH_ATTENTION_FIRST=1): headline
        # throughput + kernel ladder are the gating artifacts, the second
        # model is corroboration.
        if os.environ.get("BENCH_ATTENTION_FIRST"):
            _run_attention()
        other = "lm" if MODEL == "resnet" else "resnet"
        if not os.environ.get("BENCH_SKIP_SECOND_MODEL"):
            if tpu_dead(f"throughput:{other}"):
                stages.append({"stage": f"throughput:{other}",
                               "skipped": "backend unreachable"})
            else:
                results[other] = _throughput(platform, stages, other)
                if platform is not None:
                    # this stage's outcome is the freshest liveness evidence
                    tpu_suspect = (results[other] is None
                                   or bool(results[other].get("partial_rc")))
    except Exception as e:  # noqa: BLE001 — the one JSON line must still print
        stages.append({"stage": "orchestrator", "err": repr(e)[:300]})
    if not attention_done:
        _run_attention()
    cp = native = zero = elastic = sched = None
    try:
        zero = _zero_ab(stages, platform)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "zero_ab", "err": repr(e)[:300]})
    try:
        elastic = _elastic_ab(stages, platform)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "elastic_ab", "err": repr(e)[:300]})
    try:
        sched = _sched_policy(stages)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "sched_policy", "err": repr(e)[:300]})
    try:
        cp = _control_plane(stages)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "control_plane", "err": repr(e)[:300]})
    try:
        native = _native(stages)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "native", "err": repr(e)[:300]})

    headline = results.get(MODEL)
    if headline is None:
        headline = {
            "metric": f"{MODEL}_train_throughput",
            "value": 0.0,
            "unit": "images/sec" if MODEL == "resnet" else "tokens/sec",
            "vs_baseline": 0.0,
            "error": "all bench stages failed",
        }
    other = "lm" if MODEL == "resnet" else "resnet"
    if results.get(other):
        headline[other] = results[other]
    if attention:
        headline["attention"] = attention
    if cp:
        if "local" in cp:
            headline["time_to_all_running_sec"] = (
                cp["local"].get("time_to_all_running_sec"))
        headline["control_plane"] = cp
    if native:
        headline["native"] = native
    if zero:
        headline["zero"] = zero
    if elastic:
        headline["elastic"] = elastic
    if sched:
        headline["sched_policy"] = sched
    headline["stages"] = stages
    print(json.dumps(_compact_summary(headline)))


def _slim_stage(s):
    """Stage entry pared to the fields the capture contract reads
    (hw_watcher.bench_complete: probe platform/ok, partial/skip flags on
    throughput/attention stages) plus short diagnostics."""
    keep = ("stage", "rc", "sec", "ok", "batch", "attempt", "platform",
            "devices", "partial_rc", "skipped", "note")
    slim = {k: s[k] for k in keep if k in s}
    if "err" in s:
        slim["err"] = str(s["err"])[:80]
    return slim


def _slim_attention(arm):
    """An attention child doc pared to its headline numbers: per-row
    timings/speedups survive, error reprs are truncated."""
    if not isinstance(arm, dict):
        return arm
    out = {"kernel_path": arm.get("kernel_path"),
           "shape": arm.get("shape")}
    rows = []
    for r in arm.get("fwd_bwd") or []:
        slim = {k: v for k, v in r.items() if not k.endswith("_error")}
        for k in r:
            if k.endswith("_error"):
                slim[k] = str(r[k])[:60]
        rows.append(slim)
    out["fwd_bwd"] = rows
    for k in ("partial_rc", "partial"):
        if k in arm:
            out[k] = arm[k]
    return out


def _compact_summary(headline):
    """The one line the driver captures.  BENCH_r04.json came back
    `parsed: null` because the full document outgrew the driver's tail
    buffer — so the full doc now goes to artifacts/bench_full.json and
    stdout's final line carries only the headline numbers plus the
    slimmed stage log the watcher's completeness check reads."""
    # Unique name per run: hw_watcher/tpu_hw_check park and promote the
    # compact lines under stamped names, and each one's full_doc pointer
    # must keep referring to ITS run — a fixed name would let the next
    # (possibly CPU-fallback) run clobber the full record of a scarce
    # on-chip capture.
    full_path = os.path.join(
        REPO, "artifacts",
        f"bench_full_{time.strftime('%Y%m%d_%H%M%S')}_{os.getpid()}.json")
    try:
        os.makedirs(os.path.dirname(full_path), exist_ok=True)
        with open(full_path, "w") as f:
            json.dump(headline, f, indent=1)
    except OSError:
        full_path = None
    compact = {k: headline.get(k) for k in
               ("metric", "value", "unit", "vs_baseline")}
    for k in ("platform", "mfu", "mfu_baseline", "partial_rc", "partial",
              "time_to_all_running_sec", "error"):
        if headline.get(k) is not None:
            compact[k] = headline[k]
    other = "lm" if MODEL == "resnet" else "resnet"
    if isinstance(headline.get(other), dict):
        o = headline[other]
        compact[other] = {k: o[k] for k in
                          ("metric", "value", "unit", "vs_baseline",
                           "platform", "mfu", "mfu_baseline", "partial_rc")
                          if o.get(k) is not None}
    attention = headline.get("attention")
    if isinstance(attention, dict):
        slim = _slim_attention(attention)
        for arm in ("gqa_arm", "window_arm"):
            if isinstance(attention.get(arm), dict):
                slim[arm] = _slim_attention(attention[arm])
        compact["attention"] = slim
    native = headline.get("native")
    if isinstance(native, dict):
        compact["native"] = {k: v for k, v in native.items()
                             if isinstance(v, (int, float, str))}
    cp = headline.get("control_plane")
    if isinstance(cp, dict):
        # keep the kind-tier status string (skipped-vs-deferred is itself
        # a finding) and the scalar timings; drop nested per-job detail
        slim_cp = {}
        for key, val in cp.items():
            if isinstance(val, dict):
                slim_cp[key] = {k: v for k, v in val.items()
                                if isinstance(v, (int, float, str))}
            elif isinstance(val, (int, float, str)):
                slim_cp[key] = val
        compact["control_plane"] = slim_cp
    compact["stages"] = [_slim_stage(s) for s in headline.get("stages", [])]
    if full_path:
        compact["full_doc"] = os.path.relpath(full_path, REPO)
    return compact


# ---------------------------------------------------------------------------
# Child: throughput (the only process that compiles the model)
# ---------------------------------------------------------------------------

def _tree_scalar(tree):
    """A cheap f32 scalar depending on every leaf (defeats dead-code elim)."""
    import jax
    import jax.numpy as jnp

    leaves = [
        jnp.sum(leaf).astype(jnp.float32)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.number)
    ]
    return sum(leaves) if leaves else jnp.float32(0)


def _window_timer(raw_step, state, batch, steps: int):
    """Compile `raw_step` scanned `steps` times inside one jit and return a
    zero-arg closure timing one window (device_get-synced steps/sec)."""
    import jax
    from jax import lax

    @jax.jit
    def run(state):
        def body(carry, _):
            new_state, metrics = raw_step(carry, batch)
            return new_state, metrics["loss"]

        final, losses = lax.scan(body, state, None, length=steps)
        # Depend on the final state (incl. the last optimizer update), not
        # just the last loss, so nothing is sliced out of the graph.
        return losses[-1], _tree_scalar(final)

    loss, chk = run(state)  # compile + first run
    jax.device_get((loss, chk))

    def time_once() -> float:
        t0 = time.perf_counter()
        out = run(state)
        jax.device_get(out)
        return steps / (time.perf_counter() - t0)

    return time_once


def child_throughput() -> None:
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()  # TPUJOB_FORCE_PLATFORM=cpu on the fallback path
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    model_kind = os.environ.get("BENCH_MODEL", "resnet")
    batch_size = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))
    windows = max(3, int(os.environ.get("BENCH_WINDOWS", "3")))

    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import make_train_step

    rng = np.random.RandomState(0)
    tx = optax.sgd(0.1, momentum=0.9)

    if model_kind == "lm":
        from tf_operator_tpu.models.transformer import (
            TransformerConfig, TransformerLM,
        )
        from tf_operator_tpu.train.step import lm_loss_fn

        seq = int(os.environ.get("BENCH_SEQ", "2048"))
        # BENCH_LM_ARCH=llama measures the llama family (RoPE/RMSNorm/
        # SwiGLU/GQA — the GQA-native kernel path) instead of GPT-style.
        arch = {}
        if os.environ.get("BENCH_LM_ARCH", "gpt") == "llama":
            arch = dict(
                use_rope=True, norm="rmsnorm", mlp="swiglu",
                num_kv_heads=int(os.environ.get("BENCH_LM_KV_HEADS", "4")),
            )
        cfg = TransformerConfig(
            vocab_size=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
            num_layers=int(os.environ.get("BENCH_LM_LAYERS", "12")),
            num_heads=int(os.environ.get("BENCH_LM_HEADS", "12")),
            d_model=int(os.environ.get("BENCH_LM_DMODEL", "768")),
            d_ff=int(os.environ.get("BENCH_LM_DFF", "3072")),
            max_len=seq, causal=True, dtype=jnp.bfloat16, **arch,
        )
        model = TransformerLM(cfg)
        tokens = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch_size, seq + 1)), jnp.int32
        )
        batch = {"tokens": tokens}
        example = tokens[:2, :-1]
        state = create_train_state(jax.random.PRNGKey(0), model, tx, example)
        # BENCH_LM_LOSS_CHUNK > 0 prices the chunked cross-entropy against
        # the same bare full-logits baseline (identical math, bounded
        # logits memory); default 0 keeps the headline metric comparable
        # across rounds.
        fw_raw = make_train_step(lm_loss_fn(
            model.apply,
            loss_chunk=int(os.environ.get("BENCH_LM_LOSS_CHUNK", "0")),
        ), jit=False)

        # Bare baseline: hand-written step, same math, and — the kernel bar
        # (VERDICT #3) — the O(T²) XLA attention instead of the flash kernel.
        bare_model = TransformerLM(
            TransformerConfig(**{**cfg.__dict__, "use_flash": False})
        )

        def bare_loss(p, b):
            logits = bare_model.apply({"params": p}, b["tokens"][:, :-1])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(
                logp, b["tokens"][:, 1:][..., None], axis=-1
            )[..., 0]
            return -jnp.mean(ll)

        params = model.init(jax.random.PRNGKey(0), example)["params"]
        opt_state = tx.init(params)

        def bare_raw(carry, b):
            p, os_ = carry
            loss, grads = jax.value_and_grad(bare_loss)(p, b)
            updates, new_os = tx.update(grads, os_, p)
            return (optax.apply_updates(p, updates), new_os), {"loss": loss}

        bare_state = (params, opt_state)
        unit, per_step = "tokens/sec", batch_size * seq
        tag = "llama_" if arch else ""
        # a chunked-CE run is a different measurement; tag it so rounds
        # can't silently mix chunked and full-logits throughput
        chunk_env = int(os.environ.get("BENCH_LM_LOSS_CHUNK", "0"))
        if chunk_env:
            tag += f"losschunk{chunk_env}_"
        metric = f"lm_{tag}train_tokens_per_sec_bf16_b{batch_size}_t{seq}"

        # Training FLOPs/token ~= 6P (dense matmuls fwd+bwd) + causal
        # attention term 6·L·d_model·T (12·L·d·T halved by the mask).
        n_params = sum(
            int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
        )
        flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.d_model * seq

        def mfu_of(tokens_per_sec):
            return tokens_per_sec * flops_per_token / V5E_PEAK_FLOPS
    else:
        from tf_operator_tpu.models.resnet import ResNet50
        from tf_operator_tpu.train.step import classification_loss_fn

        image = int(os.environ.get("BENCH_IMAGE", "224"))
        images = jnp.asarray(
            rng.randn(batch_size, image, image, 3), jnp.bfloat16
        )
        labels = jnp.asarray(rng.randint(0, 1000, batch_size), jnp.int32)
        batch = {"x": images, "label": labels}
        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        example = jnp.zeros((2, image, image, 3), jnp.bfloat16)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, example,
            init_kwargs={"train": True},
        )
        fw_raw = make_train_step(
            classification_loss_fn(model.apply, has_batch_stats=True,
                                   model_kwargs={"train": True}),
            has_batch_stats=True,
            jit=False,
        )

        variables = model.init(jax.random.PRNGKey(0), example, train=True)
        params, batch_stats = variables["params"], variables["batch_stats"]
        opt_state = tx.init(params)

        def bare_loss(p, bs, b):
            logits, updates = model.apply(
                {"params": p, "batch_stats": bs}, b["x"], train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logp, b["label"][..., None], axis=-1)[..., 0]
            return -jnp.mean(ll), updates["batch_stats"]

        def bare_raw(carry, b):
            p, bs, os_ = carry
            (loss, new_bs), grads = jax.value_and_grad(
                bare_loss, has_aux=True
            )(p, bs, b)
            updates, new_os = tx.update(grads, os_, p)
            return (optax.apply_updates(p, updates), new_bs, new_os), {"loss": loss}

        bare_state = (params, batch_stats, opt_state)
        unit, per_step = "images/sec", batch_size
        metric = f"resnet50_train_images_per_sec_bf16_b{batch_size}_i{image}"
        mfu_of = None

    def pct_spread(ws):
        return round(100.0 * (max(ws) - min(ws)) / max(ws), 2)

    import statistics

    # Interleaved arms: host load and thermal drift move THROUGHPUT over a
    # run, so timing all fw windows then all bare windows biases whichever
    # arm runs first (BENCH_r03's CPU LM "6.5% framework tax" was exactly
    # this artifact — fw windows decayed 1600->850 tokens/s under a
    # concurrent load while bare held steady).  Pairing fw/bare windows
    # back-to-back exposes both arms to the same instantaneous conditions;
    # vs_baseline is the median of per-pair ratios, which cancels drift.
    fw_timer = _window_timer(lambda s, b: fw_raw(s, b), state, batch, steps)
    fw_first = fw_timer()
    out = {
        "metric": metric,
        "value": round(fw_first * per_step, 2),
        "unit": unit,
        "vs_baseline": None,
        "windows": windows,
        "fw_windows_per_sec": [round(fw_first * per_step, 2)],
    }
    # Emit the framework arm as soon as it lands: if the flaky tunnel
    # wedges during the bare arm, the parent's _last_json still gets a
    # usable partial (vs_baseline absent, flagged) instead of nothing.
    print(json.dumps({**out, "partial": "bare arm not yet measured"}),
          flush=True)
    bare_timer = _window_timer(bare_raw, bare_state, batch, steps)
    # fw_first is for the early partial only — it was taken before the bare
    # arm's (long) compile, so pairing it with a bare window would span that
    # gap and re-admit the drift bias.  Every counted pair is back-to-back.
    fw_windows, bare_windows, ratios = [], [], []
    for _ in range(windows):
        fw_windows.append(fw_timer())
        bare_windows.append(bare_timer())
        ratios.append(fw_windows[-1] / bare_windows[-1])
    fw_sps = statistics.median(fw_windows)
    bare_sps = statistics.median(bare_windows)
    out.update(
        value=round(fw_sps * per_step, 2),
        vs_baseline=round(statistics.median(ratios), 4),
        fw_windows_per_sec=[round(w * per_step, 2) for w in fw_windows],
        fw_spread_pct=pct_spread(fw_windows),
        bare_windows_per_sec=[round(w * per_step, 2) for w in bare_windows],
        bare_spread_pct=pct_spread(bare_windows),
        pair_ratios=[round(r, 4) for r in ratios],
    )
    if model_kind == "lm" and mfu_of is not None:
        from tf_operator_tpu.ops.attention import _on_tpu

        if _on_tpu():
            out["mfu"] = round(mfu_of(fw_sps * per_step), 4)
            out["mfu_baseline"] = round(mfu_of(bare_sps * per_step), 4)
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# Child: ZeRO weight-update sharding A/B (BENCH_ZERO=1)
# ---------------------------------------------------------------------------

def child_zero() -> None:
    """lm tokens/sec with the weight update dense vs dp-sharded
    (train/zero.py), plus `lm_opt_state_bytes_per_device` both ways — the
    memory claim is exact arithmetic, the throughput pair is the measured
    cost/benefit of the reduce-scatter/all-gather layout at this dp."""
    # Virtual device fan-out must land before the first jax import.
    ndev_req = int(os.environ.get("BENCH_ZERO_DEVICES", "0"))
    if ndev_req > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={ndev_req}"
            ).strip()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from tf_operator_tpu.parallel.mesh import build_mesh
    from tf_operator_tpu.parallel.tp_rules import make_param_shardings
    from tf_operator_tpu.train.optim import lm_optimizer
    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import (
        lm_loss_fn, make_train_step, shard_batch, shard_train_state,
    )
    from tf_operator_tpu.train.zero import (
        build_zero_plan, opt_state_bytes_per_device,
    )

    ndev = len(jax.devices())
    if ndev < 2:
        print(json.dumps({"metric": "lm_zero_ab",
                          "skipped": f"dp={ndev} < 2 (nothing to shard)"}))
        return
    steps = int(os.environ.get("BENCH_STEPS", "6"))
    windows = max(3, int(os.environ.get("BENCH_WINDOWS", "3")))
    seq = int(os.environ.get("BENCH_SEQ", "256"))
    batch_size = int(os.environ.get("BENCH_BATCH", str(2 * ndev)))
    batch_size = max(ndev, batch_size // ndev * ndev)  # dp must divide batch
    cfg = TransformerConfig(
        vocab_size=int(os.environ.get("BENCH_LM_VOCAB", "8192")),
        num_layers=int(os.environ.get("BENCH_LM_LAYERS", "2")),
        num_heads=int(os.environ.get("BENCH_LM_HEADS", "4")),
        d_model=int(os.environ.get("BENCH_LM_DMODEL", "256")),
        d_ff=int(os.environ.get("BENCH_LM_DFF", "1024")),
        max_len=seq, causal=True,
    )
    mesh = build_mesh({"dp": ndev})
    model = TransformerLM(cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch_size, seq + 1)), jnp.int32)
    batch = shard_batch({"tokens": tokens}, mesh)
    example = tokens[:2, :-1]
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0), example)["params"]
    plan = build_zero_plan(
        shapes, mesh, base_specs=make_param_shardings(shapes, mesh))

    from tf_operator_tpu.analysis.hlo import collective_signature_from_text

    timers = {}
    sig_hashes = {}
    for arm, arm_plan in (("off", None), ("on", plan)):
        tx = lm_optimizer(3e-4, zero_plan=arm_plan,
                          mesh=mesh if arm_plan is not None else None)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, example, zero_plan=arm_plan)
        state = shard_train_state(state, mesh, zero_plan=arm_plan)
        raw = make_train_step(lm_loss_fn(model.apply), jit=False)
        # Per-arm collective signature (analysis/hlo.py): the hash pins
        # WHICH communication pattern each throughput number measured, so
        # an A/B regression can be told apart from a partitioner change.
        # lower+compile only — no execution, so the donation never fires
        # and `state` stays live for the timer below.
        text = jax.jit(raw, donate_argnums=(0,)).lower(
            state, batch).compile().as_text()
        _, sig_hashes[arm] = collective_signature_from_text(text)
        timers[arm] = _window_timer(raw, state, batch, steps)
    # Interleaved windows, same discipline as the main arm: both arms see
    # the same instantaneous host conditions, ratio is per-pair median.
    per_step = batch_size * seq
    on_w, off_w, ratios = [], [], []
    for _ in range(windows):
        off_w.append(timers["off"]() * per_step)
        on_w.append(timers["on"]() * per_step)
        ratios.append(on_w[-1] / off_w[-1])
    bytes_on = opt_state_bytes_per_device(plan, shapes)
    bytes_off = opt_state_bytes_per_device(None, shapes)
    print(json.dumps({
        "metric": "lm_zero_ab",
        "dp": ndev,
        "lm_opt_state_bytes_per_device": bytes_on,
        "lm_opt_state_bytes_per_device_dense": bytes_off,
        "opt_state_shrink": round(bytes_off / bytes_on, 3),
        "zero_on_tokens_per_sec": round(statistics.median(on_w), 2),
        "zero_off_tokens_per_sec": round(statistics.median(off_w), 2),
        "zero_on_vs_off": round(statistics.median(ratios), 4),
        "zero_on_collective_signature": sig_hashes["on"],
        "zero_off_collective_signature": sig_hashes["off"],
    }))


def child_elastic() -> None:
    """The elastic-resize recovery arc, measured: train the lm model at full
    dp width with a ZeRO plan, checkpoint, lose half the mesh, and time
    preemption -> restore-onto-dp/2 -> first optimizer step (the worker-side
    cost of one Resizing pass).  Then the steady-state A/B: tokens/sec on
    the shrunken mesh vs full width = the goodput an elastic job retains
    while kill-and-restart retains zero."""
    import tempfile

    ndev_req = int(os.environ.get("BENCH_ELASTIC_DEVICES", "0"))
    if ndev_req > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={ndev_req}"
            ).strip()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import statistics

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from tf_operator_tpu.parallel.mesh import build_mesh
    from tf_operator_tpu.parallel.tp_rules import make_param_shardings
    from tf_operator_tpu.train.checkpoint import CheckpointManager
    from tf_operator_tpu.train.optim import lm_optimizer
    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import (
        lm_loss_fn, make_train_step, shard_batch, shard_train_state,
    )
    from tf_operator_tpu.train.zero import build_zero_plan

    devices = jax.devices()
    full = len(devices) - len(devices) % 2
    if full < 4:
        print(json.dumps({"metric": "lm_elastic_ab",
                          "skipped": f"{len(devices)} devices < 4 "
                                     "(no mesh to halve)"}))
        return
    shrunk = full // 2
    steps = int(os.environ.get("BENCH_STEPS", "6"))
    windows = max(3, int(os.environ.get("BENCH_WINDOWS", "3")))
    seq = int(os.environ.get("BENCH_SEQ", "256"))
    batch_size = int(os.environ.get("BENCH_BATCH", str(2 * full)))
    batch_size = max(full, batch_size // full * full)  # dp must divide batch
    cfg = TransformerConfig(
        vocab_size=int(os.environ.get("BENCH_LM_VOCAB", "8192")),
        num_layers=int(os.environ.get("BENCH_LM_LAYERS", "2")),
        num_heads=int(os.environ.get("BENCH_LM_HEADS", "4")),
        d_model=int(os.environ.get("BENCH_LM_DMODEL", "256")),
        d_ff=int(os.environ.get("BENCH_LM_DFF", "1024")),
        max_len=seq, causal=True,
    )
    model = TransformerLM(cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (batch_size, seq + 1)), jnp.int32)
    example = tokens[:2, :-1]
    shapes = jax.eval_shape(
        model.init, jax.random.PRNGKey(0), example)["params"]
    raw = make_train_step(lm_loss_fn(model.apply), jit=False)

    def arm(dp, devs):
        mesh = build_mesh({"dp": dp}, devices=devs)
        plan = build_zero_plan(
            shapes, mesh, base_specs=make_param_shardings(shapes, mesh))
        tx = lm_optimizer(3e-4, zero_plan=plan, mesh=mesh)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, example, zero_plan=plan)
        state = shard_train_state(state, mesh, zero_plan=plan)
        batch = shard_batch({"tokens": tokens}, mesh)
        return mesh, plan, state, batch

    per_step = batch_size * seq
    mesh4, plan4, state4, batch4 = arm(full, devices[:full])
    full_timer = _window_timer(raw, state4, batch4, steps)
    full_w = [full_timer() * per_step for _ in range(windows)]

    # a few real optimizer steps before the save, so the restore below
    # demonstrably continues (resume_step > 0) instead of restoring init
    step4 = jax.jit(raw)
    for _ in range(2):
        state4, _m = step4(state4, batch4)
    ckpt_dir = tempfile.mkdtemp(prefix="bench-elastic-")
    mgr = CheckpointManager(ckpt_dir)
    mgr.save(state4.replace(zero_plan=plan4))
    mgr.close()

    # --- preemption: half the mesh is gone.  Everything from here to the
    # first completed optimizer step is the recovery path a Resizing pass
    # pays on the worker side: rebuild at dp/2, restore (sidecar re-shard),
    # recompile, step once.
    t0 = time.perf_counter()
    mesh2, plan2, template, batch2 = arm(shrunk, devices[:shrunk])
    mgr2 = CheckpointManager(ckpt_dir)
    restored = mgr2.restore(template)
    mgr2.close()
    step2 = jax.jit(raw)
    recovered, metrics = step2(restored, batch2)
    jax.device_get(metrics["loss"])
    time_to_recover = time.perf_counter() - t0

    shrunk_timer = _window_timer(raw, recovered, batch2, steps)
    shrunk_w = [shrunk_timer() * per_step for _ in range(windows)]
    full_rate = statistics.median(full_w)
    shrunk_rate = statistics.median(shrunk_w)
    print(json.dumps({
        "metric": "lm_elastic_ab",
        "dp_full": full,
        "dp_shrunk": shrunk,
        "resume_step": int(jax.device_get(restored.step)),
        "full_tokens_per_sec": round(full_rate, 2),
        "shrunk_tokens_per_sec": round(shrunk_rate, 2),
        "time_to_recover_sec": round(time_to_recover, 3),
        # goodput while the slice is gone: the resized job keeps training
        # at the shrunken rate; a kill-and-restart job trains at zero until
        # capacity returns (definitional, not simulated)
        "goodput_retained": round(shrunk_rate / full_rate, 4),
        "goodput_retained_kill_restart": 0.0,
    }))


# ---------------------------------------------------------------------------
# Child: attention ladder (flash vs XLA, compiled, fwd+bwd)
# ---------------------------------------------------------------------------

def child_attention() -> None:
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.attention import (
        _on_tpu, repeat_kv, flash_attention, xla_attention,
    )

    seqs = [int(s) for s in os.environ.get(
        "BENCH_ATTN_SEQS", "1024,2048,4096,8192").split(",")]
    b, h, d = (int(os.environ.get(k, v)) for k, v in
               (("BENCH_ATTN_B", "4"), ("BENCH_ATTN_H", "12"),
                ("BENCH_ATTN_D", "64")))
    # kv heads < h exercises the GQA-native kernel path (k/v mapped to
    # query groups in-kernel); the XLA arm widens k/v explicitly, so the
    # speedup row also prices the avoided repeat traffic.
    kv_h = int(os.environ.get("BENCH_ATTN_KV_H", str(h)))
    reps = int(os.environ.get("BENCH_ATTN_REPS", "5"))
    # Sliding-window arm: time windowed flash vs full-causal flash at the
    # same seq — the banded-grid win (O(T*w) FLOPs+DMA vs O(T^2)).
    window = int(os.environ.get("BENCH_ATTN_WINDOW", "0")) or None
    rows = []
    for t in seqs:
        key = jax.random.PRNGKey(0)
        kq, kk_, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (b, h, t, d)).astype(jnp.bfloat16)
        k = jax.random.normal(kk_, (b, kv_h, t, d)).astype(jnp.bfloat16)
        v = jax.random.normal(kv_, (b, kv_h, t, d)).astype(jnp.bfloat16)
        g = jnp.ones((b, h, t, d), jnp.bfloat16)

        def timed(fn):
            grad = jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(
                    fn(q, k, v).astype(jnp.float32) * g.astype(jnp.float32)),
                argnums=(0, 1, 2)))
            out = grad(q, k, v)  # compile
            jax.device_get(_tree_scalar(out))
            t0 = time.perf_counter()
            for _ in range(reps):
                out = grad(q, k, v)
            jax.device_get(_tree_scalar(out))
            return (time.perf_counter() - t0) / reps

        # Time each arm independently: at long seq the O(T²) XLA arm can
        # OOM where the flash kernel runs fine — that asymmetry IS the
        # result, so an XLA failure must not discard the flash number.
        row = {"seq": t}
        if kv_h != h:
            row["kv_heads"] = kv_h

        if window:
            # Window arm: full-causal flash is the baseline (XLA would
            # conflate the mask change with the kernel difference).  Skips
            # the XLA/autotune section and falls through to the common
            # per-row emit.
            row["window"] = window
            full_s = win_s = None
            try:
                full_s = timed(lambda q, k, v: flash_attention(q, k, v, True))
                row["flash_full_ms"] = round(full_s * 1e3, 3)
            except Exception as e:  # noqa: BLE001
                row["flash_full_error"] = repr(e)[:200]
            try:
                win_s = timed(lambda q, k, v: flash_attention(
                    q, k, v, True, window=window))
                row["flash_window_ms"] = round(win_s * 1e3, 3)
            except Exception as e:  # noqa: BLE001
                row["flash_window_error"] = repr(e)[:200]
            if full_s and win_s:
                row["window_speedup"] = round(full_s / win_s, 3)
            flash_s = xla_s = None  # no tune gate for this arm
        else:
            def widened_xla(q, k, v):
                return xla_attention(q, *repeat_kv(q, k, v), causal=True)

            flash_s = xla_s = None
            try:
                flash_s = timed(lambda q, k, v: flash_attention(q, k, v, True))
                row["flash_ms"] = round(flash_s * 1e3, 3)
            except Exception as e:  # noqa: BLE001
                row["flash_error"] = repr(e)[:200]
            try:
                xla_s = timed(widened_xla)
                row["xla_ms"] = round(xla_s * 1e3, 3)
            except Exception as e:  # noqa: BLE001 — e.g. OOM on the O(T²) path
                row["xla_error"] = repr(e)[:200]
            if flash_s and xla_s:  # ratio from raw timings, rounded for display
                row["speedup"] = round(xla_s / flash_s, 3)
        # Tune-until-it-wins (VERDICT r03 #2): when the default 128x128
        # tiling doesn't clearly beat XLA on chip, search block shapes and
        # record the tuned number alongside.  "auto" gates on the observed
        # ratio so flaky-window bench time is only spent where it matters;
        # BENCH_ATTN_AUTOTUNE=1 forces the search, =0 disables it.
        mode = os.environ.get("BENCH_ATTN_AUTOTUNE", "auto")
        # "1" forces the search even off-TPU (autotune itself supports the
        # fallback path, useful for exercising the plumbing); "auto" only
        # spends chip time when the default tiling isn't clearly winning.
        want_tune = (mode == "1" or (
            mode == "auto" and _on_tpu() and flash_s and xla_s
            and row.get("speedup", 99) < 1.05))
        if want_tune:
            try:
                from tf_operator_tpu.ops.autotune import tune_flash_blocks

                tuned = tune_flash_blocks(
                    b, h, t, d, kv_h=kv_h, causal=True, reps=reps)
                if "block_q" in tuned:
                    row["tuned_blocks"] = [tuned["block_q"], tuned["block_k"]]
                    flash_t = timed(lambda q, k, v: flash_attention(
                        q, k, v, True, None,
                        tuned["block_q"], tuned["block_k"]))
                    row["flash_tuned_ms"] = round(flash_t * 1e3, 3)
                    if xla_s:
                        row["speedup_tuned"] = round(xla_s / flash_t, 3)
                else:
                    row["autotune_error"] = tuned.get("error", "")[:200]
            except Exception as e:  # noqa: BLE001
                row["autotune_error"] = repr(e)[:200]
        rows.append(row)
        # Emit after every row: a tunnel wedge mid-ladder keeps the rows
        # already measured (parent takes the last complete JSON line).
        print(json.dumps({
            "fwd_bwd": rows, "shape": {"b": b, "h": h, "d": d},
            # Off-TPU flash_attention resolves to xla_attention, so both
            # arms time the same code — flag that so the rows can't be
            # misread as a kernel result.
            "kernel_path": "pallas" if _on_tpu() else "xla-fallback (no kernel)",
        }), flush=True)


# ---------------------------------------------------------------------------
# Child: control plane (time-to-all-Running on the local process runtime)
# ---------------------------------------------------------------------------

def _resnet_shaped_job(name, replicas, command):
    from tf_operator_tpu.api.core import (
        Container, ObjectMeta, PodTemplateSpec,
    )
    from tf_operator_tpu.api.types import (
        ReplicaSpec, ReplicaType, TPUJob, TPUJobSpec,
    )

    return TPUJob(
        metadata=ObjectMeta(name=name),
        spec=TPUJobSpec(replica_specs={
            ReplicaType.WORKER: ReplicaSpec(
                replicas=replicas,
                template=PodTemplateSpec(containers=[Container(
                    name="tensorflow", image="local", command=command,
                )]),
            )
        }),
    )


def child_control_plane() -> None:
    import tempfile

    from tf_operator_tpu.api.core import PodPhase
    from tf_operator_tpu.api.constants import LABEL_JOB_NAME
    from tf_operator_tpu.controller.controller import TPUJobController
    from tf_operator_tpu.runtime.local import LocalProcessCluster
    from tf_operator_tpu.sdk.client import TPUJobClient

    replicas = int(os.environ.get("BENCH_CP_REPLICAS", "4"))
    workdir = tempfile.mkdtemp(prefix="bench-cp-")
    cluster = LocalProcessCluster(workdir=workdir)
    controller = TPUJobController(cluster, threadiness=2,
                                  resolver=cluster.resolver)
    controller.start()
    client = TPUJobClient(cluster)
    try:
        # ResNet-shaped TFJob (BASELINE.md: examples/v1 ResNet-50): N workers;
        # the container just has to reach Running, so it idles.
        job = _resnet_shaped_job(
            "bench-cp", replicas,
            [sys.executable, "-c", "import time; time.sleep(120)"],
        )
        t0 = time.perf_counter()
        client.create(job)
        deadline = time.time() + 120
        while time.time() < deadline:
            pods = cluster.list_pods(
                selector={LABEL_JOB_NAME: "bench-cp"})
            if (len(pods) == replicas
                    and all(p.status.phase == PodPhase.RUNNING for p in pods)
                    and client.is_job_running("bench-cp")):
                break
            time.sleep(0.02)
        else:
            print(json.dumps({"error": "never reached all-Running"}))
            return
        dt = time.perf_counter() - t0
        print(json.dumps({"time_to_all_running_sec": round(dt, 3),
                          "replicas": replicas}))
    finally:
        try:
            client.delete("bench-cp")
        except Exception:  # noqa: BLE001
            pass
        controller.stop()
        cluster.close()


# ---------------------------------------------------------------------------
# Child: scheduling-policy soak (policy queue under mixed load + faults)
# ---------------------------------------------------------------------------

def child_sched_policy() -> None:
    """Mixed-priority churn through the policy queue (pure control plane):
    BENCH_SCHED_JOBS short preemptible low/batch single-worker gangs from
    two weighted tenants arrive in waves against a pool sized for ~8 of
    them, while BENCH_SCHED_BIG pool-scale high-class gangs drop in at
    intervals — each must preempt or out-queue its way to fully-Running.
    A seeded FaultPlan plus a scripted create-pod FaultRule runs the whole
    time, and one of the two controller replicas is crash-killed (no lease
    release) halfway through.  Emits p50/p99 submit->all-Running per
    priority class, the Jain index of the weighted tenant dominant shares,
    and the preemption count."""
    import threading

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from testutil import new_tpujob

    from tf_operator_tpu.api.core import PodPhase
    from tf_operator_tpu.api.types import (
        PRIORITY_CLASSES,
        ReplicaType,
        RestartPolicy,
        SchedulingSpec,
        TPUTopology,
    )
    from tf_operator_tpu.controller.controller import TPUJobController
    from tf_operator_tpu.runtime import conditions
    from tf_operator_tpu.runtime.cluster import InMemoryCluster
    from tf_operator_tpu.runtime.faults import (
        FAULT_SERVER_ERROR,
        Fault,
        FaultInjector,
        FaultPlan,
        FaultRule,
        FaultyCluster,
    )
    from tf_operator_tpu.runtime.policy import jain_index
    from tf_operator_tpu.runtime.reconciler import ReconcilerConfig
    from tf_operator_tpu.runtime.scheduler import GangScheduler
    from tf_operator_tpu.runtime.shardlease import ShardLeaseConfig
    from tf_operator_tpu.utils import metrics

    jobs_total = int(os.environ.get("BENCH_SCHED_JOBS", "2000"))
    wave = int(os.environ.get("BENCH_SCHED_WAVE", "200"))
    big_gangs = int(os.environ.get("BENCH_SCHED_BIG", "3"))
    total_chips = int(os.environ.get("BENCH_SCHED_CHIPS", "64"))
    weights = {"ten-a": 2.0, "ten-b": 1.0}

    rules = [FaultRule(fault=Fault(FAULT_SERVER_ERROR, status=500,
                                   message="bench-injected"),
                       op="create_pod", path="short-", times=8)]
    injector = FaultInjector(FaultPlan(seed=20260807, rate=0.02, rules=rules,
                                       latency_range=(0.0, 0.002)))
    inner = InMemoryCluster()
    faulty = FaultyCluster(inner, injector)
    scheduler = GangScheduler(inner, total_chips=total_chips,
                              tenant_weights=weights)
    # A shared scheduler must not be gated on one replica's shard split.
    scheduler.owns_gang = lambda key: True
    fleet = [
        TPUJobController(
            faulty,
            config=ReconcilerConfig(enable_gang_scheduling=True,
                                    reconciler_sync_loop_period=0.2),
            threadiness=2,
            shards=4,
            shard_lease=ShardLeaseConfig(lease_duration=1.0,
                                         renew_period=0.15),
            identity=f"replica-{i}",
        )
        for i in range(2)
    ]
    for c in fleet:
        c.gang_scheduler = scheduler

    def short_job(i):
        job = new_tpujob(worker=1, name=f"short-{i:05d}",
                         restart_policy=RestartPolicy.EXIT_CODE)
        job.spec.replica_specs[ReplicaType.WORKER].tpu = TPUTopology(
            accelerator="v5litepod", topology="2x4")  # 8 chips
        job.spec.scheduling = SchedulingSpec(
            priority_class=("low", "batch")[i % 2],
            tenant=("ten-a", "ten-b")[i % 2],
            preemptible=True,
        )
        return job

    def big_job(i):
        job = new_tpujob(worker=4, name=f"big-{i}",
                         restart_policy=RestartPolicy.EXIT_CODE)
        job.spec.replica_specs[ReplicaType.WORKER].tpu = TPUTopology(
            accelerator="v5litepod", topology="2x4")
        job.spec.scheduling = SchedulingSpec(priority_class="high")
        return job

    stop = threading.Event()
    state_lock = threading.Lock()
    expected = {}   # name -> (replicas, priority_class), set at submission
    t_submit = {}   # name -> wall-clock submit time
    t_running = {}  # name -> wall-clock all-Running time (kubelet-stamped)
    share_samples = {t: [] for t in weights}

    def kubelet():
        """Promote Pending pods; once a job's full gang is Running, stamp
        its time-to-all-running and only THEN complete it — the stamp is
        taken in the same sweep that observes the state, so a short job's
        Running window can never be missed by a sampler race."""
        while not stop.is_set():
            by_job = {}
            for pod in inner.list_pods():
                by_job.setdefault(
                    pod.metadata.labels.get("job-name"), []).append(pod)
            with state_lock:
                exp = dict(expected)
            for name, plist in by_job.items():
                info = exp.get(name)
                if info is None:
                    continue
                for p in plist:
                    if p.status.phase == PodPhase.PENDING:
                        try:
                            inner.set_pod_phase(
                                "default", p.metadata.name, PodPhase.RUNNING)
                        except Exception:  # noqa: BLE001 — deleted mid-sweep
                            continue
                running = [p for p in plist
                           if p.status.phase == PodPhase.RUNNING]
                with state_lock:
                    stamped = name in t_running
                    if not stamped and len(running) == info[0]:
                        t_running[name] = time.time()
                        stamped = True
                if stamped:
                    for p in running:
                        try:
                            inner.set_pod_phase(
                                "default", p.metadata.name,
                                PodPhase.SUCCEEDED, exit_code=0)
                        except Exception:  # noqa: BLE001
                            continue
            for tenant in weights:
                v = metrics.tenant_dominant_share.value(tenant)
                if v:
                    share_samples[tenant].append(v)
            stop.wait(0.01)

    def submit(job, replicas, cls):
        with state_lock:
            expected[job.metadata.name] = (replicas, cls)
            t_submit[job.metadata.name] = time.time()
        inner.create_job(job)

    for c in fleet:
        c.start()
    kubelet_thread = threading.Thread(target=kubelet, daemon=True,
                                      name="sched-policy-kubelet")
    kubelet_thread.start()
    try:
        waves = max(1, (jobs_total + wave - 1) // wave)
        big_at = {max(1, (w + 1) * waves // (big_gangs + 1))
                  for w in range(big_gangs)} if big_gangs else set()
        submitted = 0
        killed = False
        for w in range(waves):
            for _ in range(min(wave, jobs_total - submitted)):
                job = short_job(submitted)
                submit(job, 1, job.spec.scheduling.priority_class)
                submitted += 1
            if w in big_at:
                idx = sorted(big_at).index(w)
                submit(big_job(idx), 4, "high")
            if not killed and w >= waves // 2:
                # mid-soak crash: no lease release, no graceful handoff
                fleet[0].shard_manager.stop(release=False)
                fleet[0].stop()
                killed = True
            # bound the backlog so the policy sweep cost stays realistic
            # (an arrival process, not one 2000-deep instantaneous queue)
            deadline = time.time() + 120
            while time.time() < deadline:
                with state_lock:
                    backlog = submitted - len(t_running)
                if backlog < wave:
                    break
                time.sleep(0.05)
        if not killed and len(fleet) > 1:
            fleet[0].shard_manager.stop(release=False)
            fleet[0].stop()

        def all_done():
            return all(conditions.is_succeeded(j.status)
                       for j in inner.list_jobs())

        deadline = time.time() + 300
        while time.time() < deadline and not all_done():
            time.sleep(0.2)
        if not all_done():
            stuck = [j.metadata.name for j in inner.list_jobs()
                     if not conditions.is_succeeded(j.status)]
            print(json.dumps({"error": f"{len(stuck)} jobs never finished",
                              "stuck": stuck[:10]}))
            return

        classes = {}
        unmeasured = 0
        with state_lock:
            for name, (_replicas, cls) in expected.items():
                if name not in t_running:
                    unmeasured += 1
                    continue
                classes.setdefault(cls, []).append(
                    t_running[name] - t_submit[name])
        per_class = {}
        for cls, waits in classes.items():
            waits.sort()
            per_class[cls] = {
                "n": len(waits),
                "p50_s": round(waits[len(waits) // 2], 4),
                "p99_s": round(waits[min(len(waits) - 1,
                                         int(0.99 * len(waits)))], 4),
            }
        mean_shares = [sum(v) / len(v)
                       for v in share_samples.values() if v]
        preempted = sum(metrics.preemptions.value(c)
                        for c in PRIORITY_CLASSES)
        print(json.dumps({
            "jobs": jobs_total,
            "big_gangs": big_gangs,
            "pool_chips": total_chips,
            "classes": per_class,
            "fairness_jain": round(jain_index(mean_shares), 4),
            "preemptions": preempted,
            "faults_injected": len(injector.trace),
            "unmeasured": unmeasured,
        }))
    finally:
        stop.set()
        kubelet_thread.join(timeout=5)
        for c in fleet[1:]:
            c.stop()


# ---------------------------------------------------------------------------
# Child: control plane over the k8s wire (fake apiserver + kubelet sim)
# ---------------------------------------------------------------------------

def child_k8s_control_plane() -> None:
    """The reference's tier-2 shape (e2e_testing.md:25-40) without a real
    cluster: the SAME controller drives KubernetesCluster over actual HTTP
    against tests/fake_apiserver.py; a kubelet thread marks scheduled pods
    Running.  Reports submit→all-Running for the ResNet-shaped 4-worker job
    and a 100-job single-worker soak."""
    import threading

    sys.path.insert(0, os.path.join(REPO, "tests"))
    from fake_apiserver import FakeApiServer

    from tf_operator_tpu.controller.controller import TPUJobController
    from tf_operator_tpu.runtime.k8s import KubernetesCluster

    server = FakeApiServer()
    base_url = server.start()
    stop = threading.Event()

    def kubelet():
        """Mark every pending pod Running, like a kubelet admitting it."""
        while not stop.is_set():
            pods = server.objects("pods")  # returns a fresh copy under lock
            for name, obj in pods.items():
                if not (obj.get("status") or {}).get("phase"):
                    server.set_pod_status(
                        "default", name,
                        {"phase": "Running", "containerStatuses": [
                            {"name": "tensorflow", "state": {"running": {}}}
                        ]},
                    )
            stop.wait(0.01)

    kubelet_thread = threading.Thread(target=kubelet, daemon=True)
    from tf_operator_tpu.runtime.k8s import KubeConfig
    from tf_operator_tpu.runtime.reconciler import ReconcilerConfig

    # Unthrottled by default: this child MEASURES control-plane speed, and
    # the client-side QPS limiter (server --qps/--burst, default 5/10)
    # would dominate the number.  BENCH_K8S_QPS opts the soak into a
    # throttled run; the throttled-convergence property itself is pinned in
    # tests/test_throttle.py::test_throttled_hundred_job_soak.
    cluster = KubernetesCluster(
        KubeConfig(host=base_url, namespace="default"), namespace="default",
        qps=float(os.environ.get("BENCH_K8S_QPS", "0")),
        burst=int(os.environ.get("BENCH_K8S_BURST", "10")))
    # Informer + sharded reconcile core (docs/informer-cache.md): the soak
    # measures the scaled control plane by default; BENCH_K8S_SHARDS=1
    # reproduces the pre-sharding single-queue shape.
    controller = TPUJobController(
        cluster, config=ReconcilerConfig(reconciler_sync_loop_period=0.25),
        threadiness=4,
        shards=int(os.environ.get("BENCH_K8S_SHARDS", "4")))
    controller.start()
    kubelet_thread.start()
    out = {}
    try:
        from tf_operator_tpu.api.core import PodPhase
        from tf_operator_tpu.api.constants import LABEL_JOB_NAME
        from tf_operator_tpu.sdk.client import TPUJobClient

        client = TPUJobClient(cluster)

        def wait_running(name, replicas, deadline_s):
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                pods = cluster.list_pods(selector={LABEL_JOB_NAME: name})
                if (len(pods) == replicas and all(
                        p.status.phase == PodPhase.RUNNING for p in pods)
                        and client.is_job_running(name)):
                    return True
                time.sleep(0.02)
            return False

        t0 = time.perf_counter()
        client.create(_resnet_shaped_job("bench-k8s", 4, ["sleep", "600"]))
        if not wait_running("bench-k8s", 4, 60):
            print(json.dumps({"error": "k8s path never reached all-Running"}))
            return
        out["k8s_time_to_all_running_sec"] = round(
            time.perf_counter() - t0, 3)

        def count_running(prefix, n):
            """Server-side Running count: reads the fixture's store dict
            directly so the poll adds zero HTTP traffic — the request
            counters below then measure the CONTROLLER, not the poller."""
            running = 0
            for jname, obj in server.objects("tpujobs").items():
                if not jname.startswith(prefix):
                    continue
                for cond in ((obj.get("status") or {}).get("conditions")
                             or []):
                    if (cond.get("type") == "Running"
                            and cond.get("status") in (True, "True")):
                        running += 1
                        break
            return running

        def soak(prefix, n, deadline_s):
            """Submit n single-worker jobs; returns (wall_sec or None,
            apiserver requests during the soak, non-watch GETs during the
            soak) — the per-sync traffic evidence next to the wall-clock."""
            req0 = len(server.requests)
            t0 = time.perf_counter()
            for i in range(n):
                client.create(_resnet_shaped_job(
                    f"{prefix}{i}", 1, ["sleep", "600"]))
            deadline = time.time() + deadline_s
            while time.time() < deadline:
                if count_running(prefix, n) == n:
                    break
                time.sleep(0.05)
            wall = time.perf_counter() - t0
            reqs = list(server.requests[req0:])
            gets = sum(1 for m, p in reqs
                       if m == "GET" and "watch=true" not in p)
            if count_running(prefix, n) != n:
                return None, len(reqs), gets
            return wall, len(reqs), gets

        # 100-job soak through the same wire path.
        n = int(os.environ.get("BENCH_K8S_SOAK_JOBS", "100"))
        wall, reqs, gets = soak("soak-", n, 180)
        if wall is None:
            out["error"] = (f"soak: only {count_running('soak-', n)}/{n} "
                            "jobs Running")
        else:
            out[f"k8s_soak_{n}_jobs_sec"] = round(wall, 3)
            out["k8s_soak_api_requests_per_job"] = round(reqs / n, 2)
            out["k8s_soak_api_reads_per_job"] = round(gets / n, 2)

        # 1,000-job arm, env-gated like BENCH_K8S_QPS so the default bench
        # stays fast (ROADMAP item 1's scale gate; the informer + shards
        # are what make it converge without an O(N) request storm).
        if "error" not in out and os.environ.get("BENCH_K8S_SOAK_1K") == "1":
            n1k = 1000
            wall, reqs, gets = soak("soak1k-", n1k, 600)
            if wall is None:
                out["error"] = (f"1k soak: only "
                                f"{count_running('soak1k-', n1k)}/{n1k} "
                                "jobs Running")
            else:
                out[f"k8s_soak_{n1k}_jobs_sec"] = round(wall, 3)
                out["k8s_soak_1k_api_requests_per_job"] = round(reqs / n1k, 2)
                out["k8s_soak_1k_api_reads_per_job"] = round(gets / n1k, 2)

        # 10,000-job arm (ROADMAP item 1's next-100x gate), env-gated:
        # a FEDERATED fleet — BENCH_K8S_REPLICAS extra controller replicas
        # join via shard leases (docs/federation.md) and split the shard
        # space with the primary — drives the soak over the same wire.
        # Emits the wall clock, per-job status-write cost (the coalescing
        # evidence next to it), and each replica's pooled queue-latency
        # p99 from the existing shard metrics.
        if "error" not in out and os.environ.get("BENCH_K8S_SOAK_10K") == "1":
            from tf_operator_tpu.runtime.shardlease import ShardLeaseConfig

            n10k = int(os.environ.get("BENCH_K8S_SOAK_10K_JOBS", "10000"))
            n_replicas = int(os.environ.get("BENCH_K8S_REPLICAS", "3"))
            shards = int(os.environ.get("BENCH_K8S_SHARDS", "4"))
            fleet = [controller]
            # the primary joins the lease protocol too: replace its
            # all-shards default with a manager (constructed controllers
            # without one own everything implicitly, which would conflict)
            from tf_operator_tpu.runtime.shardlease import ShardLeaseManager

            lease_cfg = lambda: ShardLeaseConfig(  # noqa: E731
                num_shards=shards, lease_duration=10.0, renew_period=2.0)
            controller.shard_manager = ShardLeaseManager(
                cluster, "bench-r0", lease_cfg(),
                on_adopt=controller._on_shard_adopted,
                on_drop=controller._on_shard_dropped)
            controller.shard_manager.start()
            for i in range(1, n_replicas):
                peer = TPUJobController(
                    cluster,
                    config=ReconcilerConfig(
                        reconciler_sync_loop_period=0.25),
                    threadiness=4, shards=shards,
                    shard_lease=lease_cfg(), identity=f"bench-r{i}")
                peer.start()
                fleet.append(peer)
            writes0 = sum(c.status_writer.counters()["writes"]
                          for c in fleet)
            coalesced0 = sum(c.status_writer.counters()["coalesced"]
                             for c in fleet)
            try:
                wall, reqs, gets = soak("soak10k-", n10k, 3600)
                if wall is None:
                    out["error"] = (
                        f"10k soak: only "
                        f"{count_running('soak10k-', n10k)}/{n10k} "
                        "jobs Running")
                else:
                    out["k8s_soak_10000_jobs_sec"] = round(wall, 3)
                    out["k8s_soak_10k_api_requests_per_job"] = round(
                        reqs / n10k, 2)
                    writes = sum(c.status_writer.counters()["writes"]
                                 for c in fleet) - writes0
                    coalesced = sum(
                        c.status_writer.counters()["coalesced"]
                        for c in fleet) - coalesced0
                    out["k8s_soak_10k_status_writes_per_job"] = round(
                        writes / n10k, 2)
                    out["k8s_soak_10k_status_writes_coalesced"] = coalesced
                    out["k8s_soak_10k_queue_p99_sec_per_replica"] = [
                        round(c.work_queue.stats()["latency"]["p99"], 4)
                        for c in fleet]
                    out["k8s_soak_10k_replicas"] = n_replicas
            finally:
                for peer in fleet[1:]:
                    peer.stop()
        print(json.dumps(out))
    finally:
        stop.set()
        controller.stop()
        cluster.close()
        server.stop()


# ---------------------------------------------------------------------------
# Child: native transports vs Python (CPU micro-bench)
# ---------------------------------------------------------------------------

def child_native() -> None:
    import numpy as np

    out = {}

    # --- parameter server: push+pull round-trips over ~8MB of params -------
    from tf_operator_tpu.train import native_ps, ps

    rng = np.random.RandomState(0)
    params = {f"w{i}": rng.randn(256, 1024).astype(np.float32)
              for i in range(8)}  # 8MB total
    grads = {k: np.ones_like(v) for k, v in params.items()}
    reps = int(os.environ.get("BENCH_PS_REPS", "30"))
    nbytes = sum(v.nbytes for v in params.values())

    def time_ps(client):
        client.pull()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            client.push(grads)
            client.pull()
        dt = time.perf_counter() - t0
        client.close()
        # push+pull moves the full param set both ways each rep
        return 2 * reps * nbytes / dt / 1e6  # MB/s

    import threading

    py_server = ps.ParameterServer(("127.0.0.1", 0), dict(params), lr=0.1)
    threading.Thread(target=py_server.serve_forever, daemon=True).start()
    py_addr = "127.0.0.1:%d" % py_server.server_address[1]
    py_mbs = time_ps(ps.PSClient([py_addr]))
    py_server.shutdown()
    out["ps_python_mb_per_sec"] = round(py_mbs, 1)

    if native_ps.native_ps_available():
        nat_server = native_ps.NativeParameterServer(
            ("127.0.0.1", 0), dict(params), lr=0.1)
        nat_addr = "127.0.0.1:%d" % nat_server.port
        nat_mbs = time_ps(native_ps.NativePSClient([nat_addr]))
        nat_server.close()
        out["ps_native_mb_per_sec"] = round(nat_mbs, 1)
        out["ps_native_speedup"] = round(nat_mbs / py_mbs, 2)
    else:
        out["ps_native_mb_per_sec"] = None
        out["ps_native_error"] = "native PS library unavailable"

    # --- data loader: synthetic ImageNet-shaped batches ---------------------
    from tf_operator_tpu.train import data as pydata
    from tf_operator_tpu.train import native_data

    batch, image, n_batches = 64, 128, 20

    def time_loader(it):
        next(it)  # warm
        t0 = time.perf_counter()
        for _ in range(n_batches):
            next(it)
        return n_batches * batch / (time.perf_counter() - t0)

    py_ips = time_loader(pydata.synthetic_images(batch, image))
    out["data_python_images_per_sec"] = round(py_ips, 1)
    if native_data.native_available():
        it = native_data.native_synthetic_images(batch, image)
        nat_ips = time_loader(iter(it))
        it.close()
        out["data_native_images_per_sec"] = round(nat_ips, 1)
        out["data_native_speedup"] = round(nat_ips / py_ips, 2)
    else:
        out["data_native_images_per_sec"] = None
        out["data_native_error"] = "native dataloader unavailable"

    print(json.dumps(out))


if __name__ == "__main__":
    if "--child-throughput" in sys.argv:
        child_throughput()
    elif "--child-zero" in sys.argv:
        child_zero()
    elif "--child-elastic" in sys.argv:
        child_elastic()
    elif "--child-attention" in sys.argv:
        child_attention()
    elif "--child-control-plane" in sys.argv:
        child_control_plane()
    elif "--child-k8s-control-plane" in sys.argv:
        child_k8s_control_plane()
    elif "--child-sched-policy" in sys.argv:
        child_sched_policy()
    elif "--child-native" in sys.argv:
        child_native()
    else:
        orchestrate()
