"""Benchmark: ResNet-50 training throughput on the attached TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline semantics (BASELINE.md): the reference publishes no numbers; the
driver target is >= 90% of bare-XLA steps/sec for the same model/batch on
the same chip.  So vs_baseline = framework_steps_per_sec / bare_xla_steps_per_sec,
where the bare-XLA baseline is a hand-written train step with no framework
abstractions (same math, same data).  >= 0.9 passes; ~1.0 means the framework
adds no overhead.

Timing methodology: on the tunneled TPU platform used here,
`block_until_ready` does NOT synchronize (measured: 8192^3 matmuls "complete"
in 25us of host time — 280x over the chip's roofline — while a device_get
after the same chain takes the real 55ms/matmul).  The only reliable sync is
a device->host transfer.  So each measured run is ONE compiled region — the
step scanned `lax.scan`-style over STEPS iterations — ended by fetching
scalars that depend on the whole chain.  This also amortizes the ~ms-scale
per-call tunnel dispatch, which would otherwise dominate and make the
comparison measure RPC overhead instead of compute.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = int(os.environ.get("BENCH_BATCH", "128"))
IMAGE = 224
STEPS = int(os.environ.get("BENCH_STEPS", "20"))


def _tree_scalar(tree):
    """A cheap f32 scalar depending on every leaf (defeats dead-code elim)."""
    import jax
    import jax.numpy as jnp

    leaves = [
        jnp.sum(leaf).astype(jnp.float32)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.number)
    ]
    return sum(leaves) if leaves else jnp.float32(0)


def _throughput(raw_step, state, batch, steps: int) -> float:
    """steps/sec for `raw_step` scanned inside one jit, synced via device_get."""
    import jax
    from jax import lax

    @jax.jit
    def run(state):
        def body(carry, _):
            new_state, metrics = raw_step(carry, batch)
            return new_state, metrics["loss"]

        final, losses = lax.scan(body, state, None, length=steps)
        # Depend on the final state (incl. the last optimizer update), not
        # just the last loss, so nothing is sliced out of the graph.
        return losses[-1], _tree_scalar(final)

    loss, chk = run(state)  # compile + first run
    jax.device_get((loss, chk))
    t0 = time.perf_counter()
    loss, chk = run(state)
    jax.device_get((loss, chk))
    return steps / (time.perf_counter() - t0)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.resnet import ResNet50
    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import classification_loss_fn, make_train_step

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(BATCH, IMAGE, IMAGE, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, BATCH), jnp.int32)
    batch = {"x": images, "label": labels}

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1, momentum=0.9)

    # --- framework path: the raw (unjitted) framework step under one scan ---
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, jnp.zeros((2, IMAGE, IMAGE, 3), jnp.bfloat16),
        init_kwargs={"train": True},
    )
    fw_raw = make_train_step(
        classification_loss_fn(model.apply, has_batch_stats=True,
                               model_kwargs={"train": True}),
        has_batch_stats=True,
        jit=False,
    )
    fw_sps = _throughput(lambda s, b: fw_raw(s, b), state, batch, STEPS)

    # --- bare-XLA baseline: same math, no framework ---
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, IMAGE, IMAGE, 3), jnp.bfloat16), train=True
    )
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    def loss_fn(p, bs, b):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, b["x"], train=True,
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, b["label"][..., None], axis=-1)[..., 0]
        return -jnp.mean(ll), updates["batch_stats"]

    def bare_raw(carry, b):
        p, bs, os_ = carry
        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, bs, b)
        updates, new_os = tx.update(grads, os_, p)
        new_p = optax.apply_updates(p, updates)
        return (new_p, new_bs, new_os), {"loss": loss}

    bare_sps = _throughput(bare_raw, (params, batch_stats, opt_state), batch, STEPS)

    images_per_sec = fw_sps * BATCH
    print(json.dumps({
        "metric": f"resnet50_train_images_per_sec_bf16_b{BATCH}",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(fw_sps / bare_sps, 4),
    }))


if __name__ == "__main__":
    main()
