"""Benchmark: ResNet-50 training throughput on the attached TPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline semantics (BASELINE.md): the reference publishes no numbers; the
driver target is >= 90% of bare-XLA steps/sec for the same model/batch on
the same chip.  So vs_baseline = framework_steps_per_sec / bare_xla_steps_per_sec,
where the bare-XLA baseline is a hand-written jit train step with no
framework abstractions (same math, same data).  >= 0.9 passes; ~1.0 means
the framework adds no overhead.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BATCH = int(os.environ.get("BENCH_BATCH", "128"))
IMAGE = 224
STEPS = int(os.environ.get("BENCH_STEPS", "20"))
WARMUP = 3


def _throughput(step_fn, state, batch, steps: int) -> float:
    # Block on the FULL output state, not just the scalar loss: the last
    # step's backward+update would otherwise still be in flight and async
    # dispatch can overlap the host loop (measured 5x-over-roofline numbers
    # without this).
    for _ in range(WARMUP):
        state, metrics = step_fn(state, batch)
    jax_block(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, batch)
    jax_block(state)
    return steps / (time.perf_counter() - t0)


def jax_block(tree):
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.resnet import ResNet50
    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import classification_loss_fn, make_train_step

    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(BATCH, IMAGE, IMAGE, 3), jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, 1000, BATCH), jnp.int32)
    batch = {"x": images, "label": labels}

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    tx = optax.sgd(0.1, momentum=0.9)

    # --- framework path ---
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, jnp.zeros((2, IMAGE, IMAGE, 3), jnp.bfloat16),
        init_kwargs={"train": True},
    )
    fw_step = make_train_step(
        classification_loss_fn(model.apply, has_batch_stats=True,
                               model_kwargs={"train": True}),
        has_batch_stats=True,
    )
    fw_sps = _throughput(fw_step, state, batch, STEPS)

    # --- bare-XLA baseline: same math, no framework ---
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, IMAGE, IMAGE, 3), jnp.bfloat16), train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt_state = tx.init(params)

    def loss_fn(p, bs, b):
        logits, updates = model.apply(
            {"params": p, "batch_stats": bs}, b["x"], train=True,
            mutable=["batch_stats"],
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, b["label"][..., None], axis=-1)[..., 0]
        return -jnp.mean(ll), updates["batch_stats"]

    @jax.jit
    def bare_step(carry, b):
        p, bs, os_ = carry
        (loss, new_bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p, bs, b)
        updates, new_os = tx.update(grads, os_, p)
        new_p = optax.apply_updates(p, updates)
        return (new_p, new_bs, new_os), {"loss": loss}

    bare_state = (params, batch_stats, opt_state)
    for _ in range(WARMUP):
        bare_state, m = bare_step(bare_state, batch)
    jax_block(bare_state)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        bare_state, m = bare_step(bare_state, batch)
    jax_block(bare_state)
    bare_sps = STEPS / (time.perf_counter() - t0)

    images_per_sec = fw_sps * BATCH
    print(json.dumps({
        "metric": f"resnet50_train_images_per_sec_bf16_b{BATCH}",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(fw_sps / bare_sps, 4),
    }))


if __name__ == "__main__":
    main()
