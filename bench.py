"""Benchmark harness — survives the flaky tunneled-TPU environment.

Prints exactly ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline semantics (BASELINE.md): the reference publishes no numbers; the
driver target is >= 90% of bare-XLA steps/sec for the same model/batch on
the same chip.  So vs_baseline = framework_steps_per_sec / bare_xla_steps_per_sec,
where the bare-XLA baseline is a hand-written train step with no framework
abstractions (same math, same data).  >= 0.9 passes; ~1.0 means the framework
adds no overhead.  That ratio measures *framework overhead vs bare XLA* and is
meaningful on any backend, so when the TPU tunnel is down (round 1: even
`jax.devices()` hung for minutes) the harness falls back to CPU rather than
producing nothing; the chosen platform is recorded in the output.

Resilience design (VERDICT.md round-1 item #1):
- The parent process never imports jax.  All jax work happens in child
  subprocesses with hard wall-clock timeouts, so a wedged backend init can
  never hang the bench.
- Backend probe: a trivial `jax.devices()` + tiny matmul child with
  bounded retries decides TPU vs CPU before any expensive compile starts.
- Batch ladder: on child failure/timeout the batch size steps down
  (128 -> 32 -> 8) so *some* number lands even on a sick chip.
- Structured output always: on total failure the single JSON line carries
  `error` + `stage` instead of a traceback.

Also measured (BASELINE.md's other target, <90 s time-to-all-Running): a
control-plane child submits a ResNet-shaped 4-worker TPUJob on the real
LocalProcessCluster runtime and reports submit->all-replicas-Running seconds
as `time_to_all_running_sec`.

Timing methodology (throughput child): on the tunneled TPU platform,
`block_until_ready` does NOT synchronize (measured: 8192^3 matmuls "complete"
in 25us of host time while a device_get after the same chain takes the real
55ms/matmul).  The only reliable sync is a device->host transfer.  So each
measured run is ONE compiled region — the step scanned `lax.scan`-style over
STEPS iterations — ended by fetching scalars that depend on the whole chain.
This also amortizes the ~ms-scale per-call tunnel dispatch.

Env knobs: BENCH_MODEL (resnet|lm), BENCH_BATCH, BENCH_STEPS, BENCH_IMAGE,
BENCH_SEQ, BENCH_FORCE_CPU=1, BENCH_PROBE_TIMEOUT, BENCH_CHILD_TIMEOUT,
BENCH_SKIP_CONTROL_PLANE=1.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

MODEL = os.environ.get("BENCH_MODEL", "resnet")
PROBE_TIMEOUT = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
CHILD_TIMEOUT = float(os.environ.get("BENCH_CHILD_TIMEOUT", "1200"))

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "d = jax.devices();"
    "x = jnp.ones((128, 128));"
    "v = jax.device_get((x @ x).sum());"
    "print('PROBE_OK', d[0].platform, len(d))"
)


# ---------------------------------------------------------------------------
# Parent: orchestration (no jax imports here)
# ---------------------------------------------------------------------------

def _run(cmd, env_extra, timeout):
    """Run a child; return (rc, stdout, stderr_tail). rc=-9 on timeout."""
    env = dict(os.environ)
    env.update(env_extra)
    env.setdefault("PYTHONPATH", REPO)
    try:
        proc = subprocess.run(
            cmd, cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        return proc.returncode, proc.stdout, proc.stderr[-2000:]
    except subprocess.TimeoutExpired as e:
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        return -9, out, f"timeout after {timeout}s"


def _last_json(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except (ValueError, TypeError):
                continue
    return None


def _probe_backend(stages):
    """Decide the platform: 'tpu'-family if the real backend answers, else cpu."""
    if os.environ.get("BENCH_FORCE_CPU"):
        stages.append({"stage": "probe", "note": "BENCH_FORCE_CPU set"})
        return None
    for attempt in range(3):
        t0 = time.time()
        rc, out, err = _run([sys.executable, "-c", _PROBE_SRC], {}, PROBE_TIMEOUT)
        dt = round(time.time() - t0, 1)
        for line in out.splitlines():
            if line.startswith("PROBE_OK"):
                _, platform, n = line.split()
                stages.append({"stage": "probe", "attempt": attempt, "ok": True,
                               "platform": platform, "devices": int(n), "sec": dt})
                if platform == "cpu":
                    # jax came up but only on CPU (libtpu missing/broken):
                    # take the small-shape CPU fallback, not the full-size
                    # TPU configuration on a CPU backend.
                    return None
                return platform
        stages.append({"stage": "probe", "attempt": attempt, "ok": False,
                       "sec": dt, "err": err[-300:]})
        time.sleep(2.0)
    return None


def _throughput(platform, stages):
    """Run the throughput child, stepping down the batch ladder on failure."""
    if platform is not None:
        start = int(os.environ.get("BENCH_BATCH", "128"))
        # only step DOWN from the starting batch — a larger rung can't
        # succeed where a smaller one failed
        ladder = [start] + [b for b in (32, 8) if b < start]
        base_env = {}
    else:
        # CPU fallback: FIXED small shapes so compile+run stay in budget —
        # deliberately ignoring any TPU-sized BENCH_* the user exported
        # (override with BENCH_CPU_BATCH only).  NOTE: JAX_PLATFORMS=cpu env
        # is NOT honored here — the sandbox's sitecustomize re-prepends the
        # axon platform — so the child forces the platform in-process via
        # TPUJOB_FORCE_PLATFORM (workloads/runner.apply_forced_platform).
        ladder = [int(os.environ.get("BENCH_CPU_BATCH", "4"))]
        base_env = {
            "TPUJOB_FORCE_PLATFORM": "cpu",
            "BENCH_IMAGE": "64",
            "BENCH_SEQ": "256",
            "BENCH_STEPS": "6",
            "BENCH_LM_VOCAB": "8192",
            "BENCH_LM_LAYERS": "2",
            "BENCH_LM_HEADS": "4",
            "BENCH_LM_DMODEL": "256",
            "BENCH_LM_DFF": "1024",
        }
    for batch in ladder:
        env = dict(base_env, BENCH_BATCH=str(batch))
        t0 = time.time()
        rc, out, err = _run(
            [sys.executable, os.path.abspath(__file__), "--child-throughput"],
            env, CHILD_TIMEOUT,
        )
        dt = round(time.time() - t0, 1)
        parsed = _last_json(out)
        stages.append({"stage": "throughput", "batch": batch, "rc": rc,
                       "sec": dt, "ok": parsed is not None,
                       **({} if parsed else {"err": err[-300:]})})
        if parsed is not None:
            parsed["platform"] = platform or "cpu"
            return parsed
    return None


def _control_plane(stages):
    """Submit→all-Running seconds on the LocalProcessCluster runtime."""
    if os.environ.get("BENCH_SKIP_CONTROL_PLANE"):
        return None
    t0 = time.time()
    rc, out, err = _run(
        [sys.executable, os.path.abspath(__file__), "--child-control-plane"],
        {"TPUJOB_FORCE_PLATFORM": "cpu"}, 240,
    )
    parsed = _last_json(out)
    ok = parsed is not None and "time_to_all_running_sec" in parsed
    entry = {"stage": "control_plane", "rc": rc,
             "sec": round(time.time() - t0, 1), "ok": ok}
    if not ok:
        entry["err"] = (parsed or {}).get("error") or err[-300:]
    stages.append(entry)
    return parsed if ok else None


def orchestrate() -> None:
    stages = []
    result = None
    try:
        platform = _probe_backend(stages)
        result = _throughput(platform, stages)
    except Exception as e:  # noqa: BLE001 — the one JSON line must still print
        stages.append({"stage": "orchestrator", "err": repr(e)[:300]})
    cp = None
    try:
        cp = _control_plane(stages)
    except Exception as e:  # noqa: BLE001
        stages.append({"stage": "control_plane", "err": repr(e)[:300]})

    if result is None:
        result = {
            "metric": f"{MODEL}_train_throughput",
            "value": 0.0,
            "unit": "images/sec" if MODEL == "resnet" else "tokens/sec",
            "vs_baseline": 0.0,
            "error": "all bench stages failed",
        }
    if cp and "time_to_all_running_sec" in cp:
        result["time_to_all_running_sec"] = cp["time_to_all_running_sec"]
    result["stages"] = stages
    print(json.dumps(result))


# ---------------------------------------------------------------------------
# Child: throughput (the only process that compiles the model)
# ---------------------------------------------------------------------------

def _tree_scalar(tree):
    """A cheap f32 scalar depending on every leaf (defeats dead-code elim)."""
    import jax
    import jax.numpy as jnp

    leaves = [
        jnp.sum(leaf).astype(jnp.float32)
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.number)
    ]
    return sum(leaves) if leaves else jnp.float32(0)


def _steps_per_sec(raw_step, state, batch, steps: int) -> float:
    """steps/sec for `raw_step` scanned inside one jit, synced via device_get."""
    import jax
    from jax import lax

    @jax.jit
    def run(state):
        def body(carry, _):
            new_state, metrics = raw_step(carry, batch)
            return new_state, metrics["loss"]

        final, losses = lax.scan(body, state, None, length=steps)
        # Depend on the final state (incl. the last optimizer update), not
        # just the last loss, so nothing is sliced out of the graph.
        return losses[-1], _tree_scalar(final)

    loss, chk = run(state)  # compile + first run
    jax.device_get((loss, chk))
    t0 = time.perf_counter()
    loss, chk = run(state)
    jax.device_get((loss, chk))
    return steps / (time.perf_counter() - t0)


def child_throughput() -> None:
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()  # TPUJOB_FORCE_PLATFORM=cpu on the fallback path
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    batch_size = int(os.environ.get("BENCH_BATCH", "128"))
    steps = int(os.environ.get("BENCH_STEPS", "20"))

    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import make_train_step

    rng = np.random.RandomState(0)
    tx = optax.sgd(0.1, momentum=0.9)

    if MODEL == "lm":
        from tf_operator_tpu.models.transformer import (
            TransformerConfig, TransformerLM,
        )
        from tf_operator_tpu.train.step import lm_loss_fn

        seq = int(os.environ.get("BENCH_SEQ", "1024"))
        cfg = TransformerConfig(
            vocab_size=int(os.environ.get("BENCH_LM_VOCAB", "32000")),
            num_layers=int(os.environ.get("BENCH_LM_LAYERS", "12")),
            num_heads=int(os.environ.get("BENCH_LM_HEADS", "12")),
            d_model=int(os.environ.get("BENCH_LM_DMODEL", "768")),
            d_ff=int(os.environ.get("BENCH_LM_DFF", "3072")),
            max_len=seq, causal=True, dtype=jnp.bfloat16,
        )
        model = TransformerLM(cfg)
        tokens = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch_size, seq + 1)), jnp.int32
        )
        batch = {"tokens": tokens}
        example = tokens[:2, :-1]
        state = create_train_state(jax.random.PRNGKey(0), model, tx, example)
        fw_raw = make_train_step(lm_loss_fn(model.apply), jit=False)

        def bare_loss(p, b):
            logits = model.apply({"params": p}, b["tokens"][:, :-1])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(
                logp, b["tokens"][:, 1:][..., None], axis=-1
            )[..., 0]
            return -jnp.mean(ll)

        params = model.init(jax.random.PRNGKey(0), example)["params"]
        opt_state = tx.init(params)

        def bare_raw(carry, b):
            p, os_ = carry
            loss, grads = jax.value_and_grad(bare_loss)(p, b)
            updates, new_os = tx.update(grads, os_, p)
            return (optax.apply_updates(p, updates), new_os), {"loss": loss}

        bare_state = (params, opt_state)
        unit, per_step = "tokens/sec", batch_size * seq
        metric = f"lm_train_tokens_per_sec_bf16_b{batch_size}_t{seq}"
    else:
        from tf_operator_tpu.models.resnet import ResNet50
        from tf_operator_tpu.train.step import classification_loss_fn

        image = int(os.environ.get("BENCH_IMAGE", "224"))
        images = jnp.asarray(
            rng.randn(batch_size, image, image, 3), jnp.bfloat16
        )
        labels = jnp.asarray(rng.randint(0, 1000, batch_size), jnp.int32)
        batch = {"x": images, "label": labels}
        model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
        example = jnp.zeros((2, image, image, 3), jnp.bfloat16)
        state = create_train_state(
            jax.random.PRNGKey(0), model, tx, example,
            init_kwargs={"train": True},
        )
        fw_raw = make_train_step(
            classification_loss_fn(model.apply, has_batch_stats=True,
                                   model_kwargs={"train": True}),
            has_batch_stats=True,
            jit=False,
        )

        variables = model.init(jax.random.PRNGKey(0), example, train=True)
        params, batch_stats = variables["params"], variables["batch_stats"]
        opt_state = tx.init(params)

        def bare_loss(p, bs, b):
            logits, updates = model.apply(
                {"params": p, "batch_stats": bs}, b["x"], train=True,
                mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logp, b["label"][..., None], axis=-1)[..., 0]
            return -jnp.mean(ll), updates["batch_stats"]

        def bare_raw(carry, b):
            p, bs, os_ = carry
            (loss, new_bs), grads = jax.value_and_grad(
                bare_loss, has_aux=True
            )(p, bs, b)
            updates, new_os = tx.update(grads, os_, p)
            return (optax.apply_updates(p, updates), new_bs, new_os), {"loss": loss}

        bare_state = (params, batch_stats, opt_state)
        unit, per_step = "images/sec", batch_size
        metric = f"resnet50_train_images_per_sec_bf16_b{batch_size}_i{image}"

    fw_sps = _steps_per_sec(lambda s, b: fw_raw(s, b), state, batch, steps)
    bare_sps = _steps_per_sec(bare_raw, bare_state, batch, steps)

    print(json.dumps({
        "metric": metric,
        "value": round(fw_sps * per_step, 2),
        "unit": unit,
        "vs_baseline": round(fw_sps / bare_sps, 4),
    }))


# ---------------------------------------------------------------------------
# Child: control plane (time-to-all-Running on the local process runtime)
# ---------------------------------------------------------------------------

def child_control_plane() -> None:
    import tempfile

    from tf_operator_tpu.api.core import (
        Container, ObjectMeta, PodPhase, PodTemplateSpec,
    )
    from tf_operator_tpu.api.constants import LABEL_JOB_NAME
    from tf_operator_tpu.api.types import (
        ReplicaSpec, ReplicaType, TPUJob, TPUJobSpec,
    )
    from tf_operator_tpu.controller.controller import TPUJobController
    from tf_operator_tpu.runtime.local import LocalProcessCluster
    from tf_operator_tpu.sdk.client import TPUJobClient

    replicas = int(os.environ.get("BENCH_CP_REPLICAS", "4"))
    workdir = tempfile.mkdtemp(prefix="bench-cp-")
    cluster = LocalProcessCluster(workdir=workdir)
    controller = TPUJobController(cluster, threadiness=2,
                                  resolver=cluster.resolver)
    controller.start()
    client = TPUJobClient(cluster)
    try:
        # ResNet-shaped TFJob (BASELINE.md: examples/v1 ResNet-50): N workers;
        # the container just has to reach Running, so it idles.
        job = TPUJob(
            metadata=ObjectMeta(name="bench-cp"),
            spec=TPUJobSpec(replica_specs={
                ReplicaType.WORKER: ReplicaSpec(
                    replicas=replicas,
                    template=PodTemplateSpec(containers=[Container(
                        name="tensorflow", image="local",
                        command=[sys.executable, "-c",
                                 "import time; time.sleep(120)"],
                    )]),
                )
            }),
        )
        t0 = time.perf_counter()
        client.create(job)
        deadline = time.time() + 120
        while time.time() < deadline:
            pods = cluster.list_pods(
                selector={LABEL_JOB_NAME: "bench-cp"})
            if (len(pods) == replicas
                    and all(p.status.phase == PodPhase.RUNNING for p in pods)
                    and client.is_job_running("bench-cp")):
                break
            time.sleep(0.02)
        else:
            print(json.dumps({"error": "never reached all-Running"}))
            return
        dt = time.perf_counter() - t0
        print(json.dumps({"time_to_all_running_sec": round(dt, 3),
                          "replicas": replicas}))
    finally:
        try:
            client.delete("bench-cp")
        except Exception:  # noqa: BLE001
            pass
        controller.stop()
        cluster.close()


if __name__ == "__main__":
    if "--child-throughput" in sys.argv:
        child_throughput()
    elif "--child-control-plane" in sys.argv:
        child_control_plane()
    else:
        orchestrate()
