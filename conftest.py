import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
