"""On-chip sliding-window/sink kernel evidence sized for a short live window.

Times compiled fwd+bwd flash attention at one long sequence in three arms —
full causal, windowed (banded grid), windowed+sink (prefix+band grid) — so
one ~2-minute tunnel window yields the banded kernels' on-chip speedup
factor and a compiled-correctness check against the f32 reference.
Emitted incrementally like the sibling micro probes (build/micro_tpu_probe
.py): a window dying mid-run keeps the earlier arms.

Usage: python build/micro_window_probe.py [out.json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else "artifacts/micro_window.json"


def emit(doc):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, OUT)


def main():
    t0 = time.time()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.attention import (
        _on_tpu, flash_attention, xla_attention,
    )

    b, h, t, d = 1, 8, 4096, 64
    w, s = 512, 4
    doc = {
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "on_tpu": _on_tpu(),
        "shape": {"b": b, "h": h, "t": t, "d": d, "window": w, "sink": s},
        "connect_sec": round(time.time() - t0, 1),
    }
    emit(doc)
    if not doc["on_tpu"]:
        doc["note"] = "not on TPU; banded-kernel evidence needs the chip"
        emit(doc)
        print(json.dumps(doc))
        return

    keys = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(keys[0], (b, h, t, d)).astype(jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, h, t, d)).astype(jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, h, t, d)).astype(jnp.bfloat16)

    def timed(fn, reps=3):
        grad = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))
        c0 = time.time()
        out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        compile_sec = time.time() - c0
        t1 = time.perf_counter()
        for _ in range(reps):
            out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        return (time.perf_counter() - t1) / reps * 1e3, compile_sec

    # correctness first (one compiled forward vs the f32 reference at a
    # truncated length — full t would OOM the O(T^2) reference check)
    tc = 1024
    out_c = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, True, window=w, sink=s))(
            q[:, :, :tc], k[:, :, :tc], v[:, :, :tc])
    ref_c = xla_attention(
        q[:, :, :tc].astype(jnp.float32), k[:, :, :tc].astype(jnp.float32),
        v[:, :, :tc].astype(jnp.float32), causal=True, window=w, sink=s)
    err = float(jnp.max(jnp.abs(out_c.astype(jnp.float32) - ref_c)))
    doc.update(compiled_fwd_max_err=round(err, 5),
               compiled_fwd_ok=bool(err < 0.05), kernel_path="pallas")
    emit(doc)

    full_ms, full_compile = timed(
        lambda q, k, v: flash_attention(q, k, v, True))
    doc.update(flash_full_ms=round(full_ms, 3),
               full_compile_sec=round(full_compile, 1))
    emit(doc)

    win_ms, win_compile = timed(
        lambda q, k, v: flash_attention(q, k, v, True, window=w))
    doc.update(flash_window_ms=round(win_ms, 3),
               window_compile_sec=round(win_compile, 1),
               window_speedup=round(full_ms / win_ms, 3))
    emit(doc)

    sink_ms, sink_compile = timed(
        lambda q, k, v: flash_attention(q, k, v, True, window=w, sink=s))
    doc.update(flash_sink_ms=round(sink_ms, 3),
               sink_compile_sec=round(sink_compile, 1),
               sink_speedup=round(full_ms / sink_ms, 3),
               total_sec=round(time.time() - t0, 1))
    emit(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
