"""Tiered test runner: junit XML + bounded flaky retries.

The reference's CI runner retries each E2E workflow up to 10x and emits
junit XML for the Prow result UI (/root/reference/py/kubeflow/tf_operator/
test_runner.py:19-66).  This is the pytest-shaped equivalent: run a tier,
write `<junit-dir>/<tier>.xml`, and if anything failed re-run ONLY the
failed node ids (collected from the junit output) up to --retries times,
writing `<tier>-retryN.xml` per attempt.  The tier passes if every test has
passed in some attempt — the policy for real-process E2E tiers whose
failures are timing flakes, not logic bugs (logic bugs fail all attempts).

A summary line `RESULT tier=<tier> attempts=<n> status=<pass|fail>` plus
`<junit-dir>/<tier>-summary.json` records what ran, what flaked, and what
genuinely failed, so a flaky pass is visible rather than silent.

The special tier `lint` runs the concurrency checker
(`python -m tf_operator_tpu.analysis`, see docs/static-analysis.md) with no
pytest or retry machinery — static findings are never flakes — emitting the
same `RESULT tier=lint ... status=...` summary line and summary JSON.

Usage:
    python build/run_tests.py --tier unit -m "not slow and not e2e and not tpu"
    python build/run_tests.py --tier local-e2e -m "slow and not e2e and not tpu" --retries 3
    python build/run_tests.py --tier lint
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import xml.etree.ElementTree as ET

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROOT = REPO  # overridable with --root (tests point it at a sandbox)


def failed_node_ids(junit_path: str) -> tuple[list[str], int]:
    """(node ids of failed/errored testcases, count of failed cases whose
    classname could not be mapped back to a file under --root).  Unmappable
    failures must be treated as hard failures by the caller — dropping them
    would let a retry of the mappable ones flip a failing tier green."""
    try:
        root = ET.parse(junit_path).getroot()
    except (ET.ParseError, OSError):
        return [], 0
    out = []
    unmappable = 0
    for case in root.iter("testcase"):
        if case.find("failure") is not None or case.find("error") is not None:
            classname = case.get("classname", "")
            name = case.get("name", "")
            # classname is dotted (tests.test_x.TestY); pytest node ids are
            # path::Class::name
            parts = classname.split(".")
            # find the module part (tests/<file>.py)
            path = None
            for i in range(len(parts), 0, -1):
                candidate = os.path.join(*parts[:i]) + ".py"
                if os.path.exists(os.path.join(ROOT, candidate)):
                    path = candidate
                    cls = parts[i:]
                    break
            if path is None:
                unmappable += 1
                continue
            node = path + "::" + "::".join(cls + [name]) if cls else path + "::" + name
            out.append(node)
    return out, unmappable


def run_pytest(args_list: list[str], junit_path: str) -> int:
    cmd = [sys.executable, "-m", "pytest", "-q",
           f"--junitxml={junit_path}", *args_list]
    print("+", " ".join(cmd), flush=True)
    return subprocess.call(cmd, cwd=ROOT)


def run_lint_tier(junit_dir: str, paths: list[str]) -> int:
    """One checker pass per target, no retries: `--tier lint`.  `paths`
    (relative to --root) default to the repo's own package (all rules,
    interprocedural included) plus the tests tree (test-hygiene rules only:
    sleep-poll, with the known-bad lint fixtures excluded).  Each pass also
    writes its machine-readable findings (`--json`) next to
    lint-summary.json so CI uploads them as one artifact set.

    The default (no-paths) run additionally sweeps every in-package
    explorer scenario through the race-checked explorer (`--race all`,
    docs/static-analysis.md#the-race-detector) under a bounded schedule
    budget — ANALYSIS_EXPLORE_BUDGET if set, else 150 — writing
    `race-findings.json` next to `lint-findings.json`.  Race findings are
    deterministic (seeded schedules), so like static findings they get no
    retries.

    The default run also regenerates the interface manifest
    (`--manifest`, docs/static-analysis.md#interface-manifest) into
    `interface-manifest.json` next to the findings documents and
    diff-gates it against the committed docs/interface-manifest.json --
    contract drift fails the tier exactly like a finding would.

    Compiled-program (HLO) pass, gated: set ANALYSIS_HLO_BUDGET=<devices>
    (>= 2) and the default run additionally captures the four train
    workloads on that many CPU virtual devices, lints the compiled
    programs (docs/static-analysis.md#hlo-rules) into `hlo-findings.json`
    and diff-gates the collective-signature snapshot against the
    committed docs/hlo-manifest.json.  Off by default — lowering and
    compiling four models costs minutes; ci.yaml turns it on."""
    if paths:
        targets = [(p if os.path.isabs(p) else os.path.join(ROOT, p), [])
                   for p in paths]
    else:
        targets = [
            (os.path.join(REPO, "tf_operator_tpu"), []),
            (os.path.join(REPO, "tests"),
             ["--rules", "sleep-poll", "--exclude", "lint_fixtures"]),
        ]
    env = dict(os.environ)
    # the checker lives in this repo's package, wherever --root points
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    rc = 0
    findings_json: list[str] = []
    used_names: set[str] = set()
    for index, (target, extra) in enumerate(targets):
        name = ("lint-findings.json" if index == 0
                else f"lint-findings-{os.path.basename(target)}.json")
        if name in used_names:  # duplicate basenames must not overwrite
            name = name[:-len(".json")] + f"-{index + 1}.json"
        used_names.add(name)
        json_path = os.path.join(junit_dir, name)
        findings_json.append(json_path)
        cmd = [sys.executable, "-m", "tf_operator_tpu.analysis", target,
               "--json", json_path, *extra]
        print("+", " ".join(cmd), flush=True)
        rc |= subprocess.call(cmd, cwd=ROOT, env=env)
    race_schedules = None
    manifest_json = None
    manifest_diff = None
    hlo_devices = None
    hlo_json = None
    hlo_status = None
    if not paths:
        race_schedules = int(os.environ.get("ANALYSIS_EXPLORE_BUDGET", "150"))
        race_json = os.path.join(junit_dir, "race-findings.json")
        findings_json.append(race_json)
        cmd = [sys.executable, "-m", "tf_operator_tpu.analysis",
               "--race", "all", "--schedules", str(race_schedules),
               "--json", race_json]
        print("+", " ".join(cmd), flush=True)
        rc |= subprocess.call(cmd, cwd=ROOT, env=env)
        # regenerate the interface manifest and gate on the committed
        # snapshot: an unreviewed contract change is a failure, not a diff
        manifest_json = os.path.join(junit_dir, "interface-manifest.json")
        committed = os.path.join(REPO, "docs", "interface-manifest.json")
        cmd = [sys.executable, "-m", "tf_operator_tpu.analysis",
               "--manifest", "--json", manifest_json, "--diff", committed]
        print("+", " ".join(cmd), flush=True)
        manifest_rc = subprocess.call(cmd, cwd=ROOT, env=env)
        manifest_diff = "clean" if manifest_rc == 0 else "drift"
        rc |= manifest_rc
        budget = int(os.environ.get("ANALYSIS_HLO_BUDGET", "0") or 0)
        if budget >= 2:
            hlo_devices = budget
            hlo_json = os.path.join(junit_dir, "hlo-findings.json")
            findings_json.append(hlo_json)
            committed_hlo = os.path.join(REPO, "docs", "hlo-manifest.json")
            cmd = [sys.executable, "-m", "tf_operator_tpu.analysis",
                   "--hlo", "all", "--devices", str(budget),
                   "--json", hlo_json, "--diff", committed_hlo]
            print("+", " ".join(cmd), flush=True)
            hlo_rc = subprocess.call(cmd, cwd=ROOT, env=env)
            hlo_status = "pass" if hlo_rc == 0 else "fail"
            rc |= hlo_rc
    status = "pass" if rc == 0 else "fail"
    with open(os.path.join(junit_dir, "lint-summary.json"), "w") as f:
        json.dump({"tier": "lint", "attempts": 1, "status": status,
                   "targets": [t for t, _extra in targets],
                   "race_schedules": race_schedules,
                   "manifest_json": manifest_json,
                   "manifest_diff": manifest_diff,
                   "hlo_devices": hlo_devices,
                   "hlo_json": hlo_json,
                   "hlo_status": hlo_status,
                   "findings_json": findings_json}, f, indent=2)
    print(f"RESULT tier=lint attempts=1 status={status}", flush=True)
    return 0 if rc == 0 else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tier", required=True)
    parser.add_argument("-m", "--marker", default=None)
    parser.add_argument("--retries", type=int, default=0,
                        help="re-runs of failed tests only (0 = strict)")
    parser.add_argument("--junit-dir", default="junit")
    parser.add_argument("--root", default=REPO,
                        help="directory to run pytest from (default: repo)")
    parser.add_argument("paths", nargs="*", default=[])
    args = parser.parse_args(argv)

    global ROOT
    ROOT = os.path.abspath(args.root)
    junit_dir = os.path.join(ROOT, args.junit_dir)
    os.makedirs(junit_dir, exist_ok=True)

    if args.tier == "lint":
        return run_lint_tier(junit_dir, list(args.paths))

    base_args = list(args.paths) or ["tests/"]
    if args.marker:
        base_args += ["-m", args.marker]

    first_xml = os.path.join(junit_dir, f"{args.tier}.xml")
    rc = run_pytest(base_args, first_xml)
    attempts = 1
    flaked: list[str] = []
    remaining, unmappable = failed_node_ids(first_xml) if rc != 0 else ([], 0)
    if unmappable:
        # failures we cannot re-run individually: the tier fails outright
        print(f"RESULT tier={args.tier} attempts=1 status=fail "
              f"({unmappable} failed case(s) unmappable to node ids)",
              flush=True)
        return 1
    if rc != 0 and not remaining:
        # pytest died before writing junit (collection error etc.) — no
        # retry target; that is a hard failure.
        print(f"RESULT tier={args.tier} attempts=1 status=fail "
              f"(no junit to retry from, rc={rc})", flush=True)
        return rc

    while remaining and attempts <= args.retries:
        retry_xml = os.path.join(
            junit_dir, f"{args.tier}-retry{attempts}.xml")
        print(f"retrying {len(remaining)} failed test(s), "
              f"attempt {attempts + 1}", flush=True)
        rc = run_pytest(remaining, retry_xml)
        attempts += 1
        if rc != 0:
            still, unmappable = failed_node_ids(retry_xml)
            if unmappable:
                print(f"retry junit has {unmappable} unmappable failed "
                      f"case(s); treating the attempt as failed", flush=True)
                break
            if not still:
                # pytest died without a parseable junit (segfault, collection
                # error): NOT a pass — everything outstanding stays failed.
                print(f"retry attempt produced no junit (rc={rc}); "
                      f"treating {len(remaining)} test(s) as failed", flush=True)
                break
        else:
            still = []
        flaked += [n for n in remaining if n not in still]
        remaining = still

    status = "pass" if not remaining else "fail"
    summary = {
        "tier": args.tier,
        "attempts": attempts,
        "status": status,
        "flaked": flaked,       # passed only on a retry — visible, not silent
        "failed": remaining,    # failed every attempt
    }
    with open(os.path.join(junit_dir, f"{args.tier}-summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    print(f"RESULT tier={args.tier} attempts={attempts} status={status}"
          + (f" flaked={len(flaked)}" if flaked else "")
          + (f" failed={len(remaining)}" if remaining else ""), flush=True)
    return 0 if status == "pass" else 1


if __name__ == "__main__":
    sys.exit(main())
