#!/bin/sh
# Bump all version surfaces in lockstep (docs/releasing.md).
# Usage: build/release.sh X.Y.Z
set -eu
VERSION="${1:?usage: build/release.sh X.Y.Z}"
case "$VERSION" in
  *[!0-9.]*|*..*|.*|*.|*.*.*.*) echo "not a semver: $VERSION" >&2; exit 1 ;;
  *.*.*) : ;;
  *) echo "not a semver (need X.Y.Z): $VERSION" >&2; exit 1 ;;
esac
ROOT="$(cd "$(dirname "$0")/.." && pwd)"

OLD="$(python -c "import sys; sys.path.insert(0, '$ROOT'); import tf_operator_tpu as m; print(m.__version__)")"

python - "$VERSION" "$OLD" <<EOF
import io, re, sys
version, old = sys.argv[1], sys.argv[2]
root = "$ROOT"

def sub(path, pattern, repl, count=1):
    with io.open(path) as f:
        src = f.read()
    out, n = re.subn(pattern, repl, src, count=count)
    if n != count:
        raise SystemExit(f"{path}: expected {count} substitution(s), got {n}")
    with io.open(path, "w") as f:
        f.write(out)

sub(f"{root}/tf_operator_tpu/__init__.py",
    r'__version__ = "[^"]+"', f'__version__ = "{version}"')
sub(f"{root}/manifests/kustomization.yaml",
    r"newTag: v[0-9.]+", f"newTag: v{version}")
sub(f"{root}/manifests/deployment.yaml",
    r"image: tpu-operator:v[0-9.]+", f"image: tpu-operator:v{version}")

# changelog stub (idempotent)
with io.open(f"{root}/CHANGELOG.md") as f:
    log = f.read()
if f"## v{version}" not in log:
    marker = f"## v{old}"
    stub = f"## v{version}\n\n- TODO: release notes.\n\n"
    log = log.replace(marker, stub + marker, 1)
    with io.open(f"{root}/CHANGELOG.md", "w") as f:
        f.write(log)
print(f"bumped {old} -> {version}")
EOF

cd "$ROOT" && python -m pytest tests/test_release.py -q
