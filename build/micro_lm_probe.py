"""On-chip LM training evidence sized for a short live window.

Companion to build/micro_tpu_probe.py (flash-vs-XLA micro): the tunneled
TPU wedges for hours with occasional ~1-minute live windows, and the full
bench's LM stage (compile + interleaved fw/bare windows) cannot finish in
one.  This captures the next-highest-value data the verdict asks for — LM
training tokens/sec and MFU on the real chip — in two escalating stages,
each emitted incrementally so a window that dies mid-run keeps whatever
landed:

  1. "tiny"  — 2L/256d model, t=512, b=2: compiles fast; proves the
     framework train step (flash kernel path included) executes on chip
     and yields a first tokens/sec + MFU datum.
  2. "base"  — the bench's default 12L/768d GPT config at t=1024, b=4:
     the headline-comparable number (BENCH_r* uses the same shape family).

MFU uses the same estimate as bench.py: flops/token ~= 6P + 6*L*d_model*T
against the v5e bf16 peak (197 TFLOP/s/chip).

Usage: python build/micro_lm_probe.py [out.json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else "artifacts/micro_lm.json"
V5E_PEAK_FLOPS = 197e12  # bench.py's MFU denominator


def emit(doc):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, OUT)


def run_stage(*, layers, d_model, heads, d_ff, vocab, seq, batch, steps=5):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tf_operator_tpu.models.transformer import (
        TransformerConfig, TransformerLM,
    )
    from tf_operator_tpu.train.state import create_train_state
    from tf_operator_tpu.train.step import lm_loss_fn, make_train_step

    t0 = time.time()
    cfg = TransformerConfig(
        vocab_size=vocab, num_layers=layers, num_heads=heads,
        d_model=d_model, d_ff=d_ff, max_len=seq, causal=True,
        dtype=jnp.bfloat16,
    )
    model = TransformerLM(cfg)
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, vocab, (batch, seq + 1)), jnp.int32)
    batch_d = {"tokens": tokens}
    tx = optax.sgd(0.1, momentum=0.9)
    state = create_train_state(
        jax.random.PRNGKey(0), model, tx, tokens[:2, :-1])
    step = make_train_step(lm_loss_fn(model.apply))

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(state.params))
    flops_per_token = 6 * n_params + 6 * layers * d_model * seq

    c0 = time.time()
    state, metrics = step(state, batch_d)
    jax.block_until_ready(metrics["loss"])
    compile_sec = time.time() - c0

    t1 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_d)
    jax.block_until_ready(metrics["loss"])
    step_sec = (time.perf_counter() - t1) / steps
    tokens_per_sec = batch * seq / step_sec

    return {
        "config": {"layers": layers, "d_model": d_model, "heads": heads,
                   "d_ff": d_ff, "vocab": vocab, "seq": seq, "batch": batch},
        "n_params": n_params,
        "compile_sec": round(compile_sec, 1),
        "timed_steps": steps,
        "step_ms": round(step_sec * 1e3, 2),
        "tokens_per_sec": round(tokens_per_sec, 1),
        "mfu": round(tokens_per_sec * flops_per_token / V5E_PEAK_FLOPS, 6),
        "loss": float(metrics["loss"]),
        "stage_sec": round(time.time() - t0, 1),
    }


def main():
    t0 = time.time()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import jax

    from tf_operator_tpu.ops.attention import _on_tpu

    doc = {
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        # _on_tpu is the framework's single source of truth for "the flash
        # kernel path is live" (it accepts aliased backends the bare
        # platform string comparison would miss).
        "on_tpu": _on_tpu(),
        "peak_flops": V5E_PEAK_FLOPS,
        "connect_sec": round(time.time() - t0, 1),
    }
    emit(doc)
    if not doc["on_tpu"]:
        doc["note"] = "not on TPU; MFU vs v5e peak would be meaningless"
        emit(doc)
        print(json.dumps(doc))
        return

    doc["tiny"] = run_stage(
        layers=2, d_model=256, heads=4, d_ff=1024,
        vocab=8192, seq=512, batch=2)
    emit(doc)  # first on-chip LM datum safe before the big compile

    doc["base"] = run_stage(
        layers=12, d_model=768, heads=12, d_ff=3072,
        vocab=32000, seq=1024, batch=4)
    doc["total_sec"] = round(time.time() - t0, 1)
    emit(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
