#!/bin/sh
# One-shot hardware validation: run whenever the (flaky) tunneled TPU is
# reachable.  Captures the compiled-kernel test tier and the full bench into
# artifacts/ so hardware evidence survives tunnel outages.
set -u
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"
STAMP="${1:-manual}"
mkdir -p artifacts

echo "== probe =="
timeout 120 python -c "import jax; print(jax.devices())" || {
  echo "TPU unreachable; aborting"; exit 1; }

echo "== hardware test tier =="
TPUJOB_TEST_PLATFORM=tpu timeout 1200 python -m pytest tests/ -m tpu -v \
  2>&1 | tail -40 | tee "artifacts/tpu_tier_${STAMP}.log"

echo "== bench (both models + attention ladder + control plane + native) =="
timeout 3600 python bench.py 2>&1 | tail -1 \
  | tee "artifacts/bench_${STAMP}.json"

echo "done: artifacts/tpu_tier_${STAMP}.log artifacts/bench_${STAMP}.json"
