#!/bin/sh
# One-shot hardware validation: run whenever the (flaky) tunneled TPU is
# reachable.  Captures the compiled-kernel test tier and the full bench into
# artifacts/ so hardware evidence survives tunnel outages.
set -u
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"
STAMP="${1:-manual}"
mkdir -p artifacts

echo "== probe =="
timeout 120 python -c "import jax; print(jax.devices())" || {
  echo "TPU unreachable; aborting"; exit 1; }

# Captures go to a temp file first.  A capture is promoted to the real
# artifact name only when its pytest summary is green; anything else
# non-empty is kept under a _partial name so a wedged-tunnel truncation
# can neither clobber a previously complete artifact nor retire a
# hw_watcher stage.  The green/complete criteria live in ONE place —
# build/hw_watcher.py (tail_green, bench_complete) — and are invoked
# here rather than re-implemented, so the two capture paths can't drift.
tier_green() { # $1 capture file (may embed a stderr tail after the marker)
  python -c 'import sys; sys.path.insert(0, "build"); from hw_watcher import file_green; sys.exit(0 if file_green(sys.argv[1]) else 1)' "$1"
}
bench_ok() { # $1 capture file
  python -c 'import sys; sys.path.insert(0, "build"); from hw_watcher import bench_complete; sys.exit(0 if bench_complete(sys.argv[1]) else 1)' "$1"
}
keep_partial() { # $1 tmp  $2 dst — park tmp at hw_watcher's _partialN name
  python -c 'import sys, os; sys.path.insert(0, "build"); from hw_watcher import next_partial; p = next_partial(sys.argv[2]); os.replace(sys.argv[1], p); print(p)' "$1" "$2"
}
record_tier() { # $1 tmp  $2 dst  $3 pytest rc
  tmp="$1"; dst="$2"; rc="$3"
  [ -s "$tmp" ] || { rm -f "$tmp"; return; }
  # Same promotion bar as hw_watcher.do_pytest: green summary AND rc=0
  # (a teardown/plugin failure after the summary line exits nonzero).
  if [ "$rc" = "0" ] && tier_green "$tmp"; then
    mv "$tmp" "$dst"
    cat "$dst"
  else
    echo "capture not green (rc=$rc); kept as $(keep_partial "$tmp" "$dst")"
  fi
}

# The tier runs in two budgeted chunks, kernel tests first: on a slow
# tunnel a single heavy test (the compiled KV-cache decode collects
# first alphabetically) can eat the whole budget, and the flash/GQA
# kernel evidence is the higher-priority capture.  The chunks exactly
# partition `pytest tests/ -m tpu`, so a green ops+rest pair is a full
# tier capture — hw_watcher.stage_done retires its tier stage on the
# pair (tpu_tier_${STAMP}.log is only accepted for legacy whole-tier
# captures; nothing writes it anymore).
# stdout and stderr are captured SEPARATELY: the summary line that
# tier_green judges lives on stdout, and the tunneled backend floods
# stderr with xla/libtpu warnings that would otherwise evict it from a
# merged tail.  The stderr tail is appended after hw_watcher's marker,
# which file_green strips before judging.
capture_tier() { # $1 out.tmp  $2 err.tmp  $3 capture.tmp
  { tail -40 "$1"
    if [ -s "$2" ]; then echo "--- stderr tail ---"; tail -10 "$2"; fi
  } > "$3"
  rm -f "$1" "$2"
}

echo "== hardware test tier: kernels (ops) first =="
TPUJOB_TEST_PLATFORM=tpu timeout 900 python -m pytest tests/test_ops.py -m tpu -v \
  > "artifacts/.tier_ops.out.tmp" 2> "artifacts/.tier_ops.err.tmp"
ops_rc=$?
capture_tier "artifacts/.tier_ops.out.tmp" "artifacts/.tier_ops.err.tmp" \
  "artifacts/.tier_ops.tmp"
record_tier "artifacts/.tier_ops.tmp" "artifacts/tpu_tier_ops_${STAMP}.log" "$ops_rc"

echo "== hardware test tier: remainder =="
TPUJOB_TEST_PLATFORM=tpu timeout 900 python -m pytest tests/ -m tpu -v \
  --ignore=tests/test_ops.py \
  > "artifacts/.tier.out.tmp" 2> "artifacts/.tier.err.tmp"
rest_rc=$?
capture_tier "artifacts/.tier.out.tmp" "artifacts/.tier.err.tmp" \
  "artifacts/.tier.tmp"
record_tier "artifacts/.tier.tmp" "artifacts/tpu_tier_rest_${STAMP}.log" "$rest_rc"

echo "== bench (both models + attention ladder + control plane + native) =="
# stdout only: bench.py's single JSON line must not be displaced by a
# trailing stderr warning (same separation rationale as the tier).
timeout 3600 python bench.py > "artifacts/.bench.out.tmp" 2> "artifacts/.bench.err.tmp"
grep -v '^[[:space:]]*$' "artifacts/.bench.out.tmp" | tail -1 > "artifacts/.bench.tmp"
rm -f "artifacts/.bench.out.tmp" "artifacts/.bench.err.tmp"
if [ -s "artifacts/.bench.tmp" ]; then
  # Promote to bench_${STAMP}.json only when the capture is a complete
  # on-TPU run (hw_watcher.bench_complete); a CPU fallback or partial is
  # kept distinctly and never overwrites a previously recorded TPU bench.
  if bench_ok "artifacts/.bench.tmp"; then
    mv "artifacts/.bench.tmp" "artifacts/bench_${STAMP}.json"
    cat "artifacts/bench_${STAMP}.json"
  else
    echo "bench capture not a complete TPU run; kept as $(keep_partial "artifacts/.bench.tmp" "artifacts/bench_${STAMP}.json")"
  fi
fi

rm -f "artifacts/.tier.tmp" "artifacts/.tier_ops.tmp" "artifacts/.bench.tmp"
echo "recorded artifacts for stamp ${STAMP}:"
ls "artifacts/tpu_tier_ops_${STAMP}.log" "artifacts/tpu_tier_rest_${STAMP}.log" \
   "artifacts/bench_${STAMP}.json" 2>/dev/null \
  || echo "(some captures produced no output and were not recorded)"
