#!/bin/sh
# One-shot hardware validation: run whenever the (flaky) tunneled TPU is
# reachable.  Captures the compiled-kernel test tier and the full bench into
# artifacts/ so hardware evidence survives tunnel outages.
set -u
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
cd "$ROOT"
STAMP="${1:-manual}"
mkdir -p artifacts

echo "== probe =="
timeout 120 python -c "import jax; print(jax.devices())" || {
  echo "TPU unreachable; aborting"; exit 1; }

# Write captures to a temp file first and only replace the artifact when
# the capture is non-empty: a wedged tunnel + timeout kill must not
# truncate a previously recorded artifact.
echo "== hardware test tier =="
TPUJOB_TEST_PLATFORM=tpu timeout 1200 python -m pytest tests/ -m tpu -v \
  2>&1 | tail -40 > "artifacts/.tier.tmp"
if [ -s "artifacts/.tier.tmp" ]; then
  mv "artifacts/.tier.tmp" "artifacts/tpu_tier_${STAMP}.log"
  cat "artifacts/tpu_tier_${STAMP}.log"
fi

echo "== bench (both models + attention ladder + control plane + native) =="
timeout 3600 python bench.py 2>&1 | tail -1 > "artifacts/.bench.tmp"
if [ -s "artifacts/.bench.tmp" ]; then
  mv "artifacts/.bench.tmp" "artifacts/bench_${STAMP}.json"
  cat "artifacts/bench_${STAMP}.json"
fi

rm -f "artifacts/.tier.tmp" "artifacts/.bench.tmp"
echo "recorded artifacts for stamp ${STAMP}:"
ls "artifacts/tpu_tier_${STAMP}.log" "artifacts/bench_${STAMP}.json" 2>/dev/null \
  || echo "(some captures produced no output and were not recorded)"
