"""Minimal on-chip kernel-perf evidence, sized for a ~1-minute live window.

The tunneled TPU wedges for hours with occasional short live windows
(artifacts/ROUND3_NOTES.md); the full bench or test tier cannot finish in
one.  This script captures the single highest-value datum — compiled Pallas
flash attention fwd+bwd wall time vs the XLA attention at one sequence
length — writing JSON incrementally so even a window that dies mid-run
leaves the flash half on disk.

Usage: python build/micro_tpu_probe.py [out.json]   (~2-3 min budget;
the flash timing alone lands within ~60-90s of a cold start)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else "artifacts/micro_flash.json"


def emit(doc):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, OUT)


def main():
    t0 = time.time()
    # TPUJOB_FORCE_PLATFORM=cpu makes the script smokeable off-chip; bare,
    # importing jax dials the tunneled TPU plugin (hangs if wedged — callers
    # probe first, and the watcher wraps this in a hard timeout).
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.attention import (
        _on_tpu, flash_attention, repeat_kv, xla_attention,
    )

    doc = {
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "on_tpu": _on_tpu(),
        "shape": {"b": 1, "h": 4, "t": 1024, "d": 64},
        "connect_sec": round(time.time() - t0, 1),
    }
    emit(doc)
    if not doc["on_tpu"]:
        doc["note"] = "not on TPU; timings would be fallback-vs-itself"
        emit(doc)
        print(json.dumps(doc))
        return

    b, h, t, d = 1, 4, 1024, 64
    keys = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(keys[0], (b, h, t, d)).astype(jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, h, t, d)).astype(jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, h, t, d)).astype(jnp.bfloat16)

    def timed(fn, reps=3):
        grad = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))
        c0 = time.time()
        out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        compile_sec = time.time() - c0
        t1 = time.perf_counter()
        for _ in range(reps):
            out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        return (time.perf_counter() - t1) / reps * 1e3, compile_sec

    flash_ms, flash_compile = timed(
        lambda q, k, v: flash_attention(q, k, v, True))
    doc.update(flash_ms=round(flash_ms, 3),
               flash_compile_sec=round(flash_compile, 1),
               kernel_path="pallas")
    emit(doc)  # flash half safe on disk before the XLA arm compiles

    xla_ms, xla_compile = timed(
        lambda q, k, v: xla_attention(q, *repeat_kv(q, k, v), causal=True))
    doc.update(xla_ms=round(xla_ms, 3), xla_compile_sec=round(xla_compile, 1),
               speedup=round(xla_ms / flash_ms, 3),
               total_sec=round(time.time() - t0, 1))
    emit(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
