"""Round-2 VERDICT weak-#1 repro, runnable on the real chip.

Before the fix, `jax.jit(flash_attention)` failed Mosaic lowering with a
(1, block_q) lse BlockSpec violating the (8, 128) tiling constraint
(artifacts/flash_repro_r03_before.log).  This script runs the exact "done"
criterion from the verdict: compiled fwd + bwd on the bench chip vs the f32
XLA reference at the tolerances of tests/test_ops.py::TestCompiledOnTPU,
for divisible (256) and non-divisible (300) sequence lengths, causal and
not.  Capture: `python build/flash_repro.py 2>&1 | tee artifacts/flash_repro_<stamp>.log`
"""
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# TPUJOB_FORCE_PLATFORM=cpu lets the script run off-chip (fallback-path
# smoke); without it, importing jax dials the tunneled TPU plugin — which
# HANGS when the tunnel is wedged, so only run bare on a live chip.
from tf_operator_tpu.workloads.runner import apply_forced_platform  # noqa: E402

apply_forced_platform()

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tf_operator_tpu.ops.attention import flash_attention, xla_attention  # noqa: E402

print("backend:", jax.default_backend(), jax.devices())
failures = 0
for t in (256, 300):
    for causal in (True, False):
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(kk, (2, 4, t, 64)).astype(jnp.bfloat16)
            for kk in jax.random.split(key, 3)
        )
        qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
        tag = f"t={t} causal={causal}"
        try:
            out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal))(q, k, v)
            ref = xla_attention(qf, kf, vf, causal=causal)
            np.testing.assert_allclose(
                np.asarray(out, np.float32), np.asarray(ref), atol=0.05, rtol=0.05
            )
            print(f"FWD OK   {tag}")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"FWD FAIL {tag}: {type(e).__name__} {str(e)[:400]}")
            continue

        def loss(attn, q, k, v):
            return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)

        try:
            grads = jax.jit(
                jax.grad(
                    lambda q, k, v: loss(lambda *a: flash_attention(*a, causal), q, k, v),
                    argnums=(0, 1, 2),
                )
            )(q, k, v)
            refs = jax.jit(
                jax.grad(
                    lambda q, k, v: loss(
                        lambda *a: xla_attention(*a, causal=causal), q, k, v
                    ),
                    argnums=(0, 1, 2),
                )
            )(qf, kf, vf)
            for name, got, want in zip("dq dk dv".split(), grads, refs):
                np.testing.assert_allclose(
                    np.asarray(got, np.float32),
                    np.asarray(want, np.float32),
                    atol=0.1,
                    rtol=0.1,
                )
            print(f"BWD OK   {tag} (dq/dk/dv within 0.1)")
        except Exception as e:  # noqa: BLE001
            failures += 1
            print(f"BWD FAIL {tag}: {type(e).__name__} {str(e)[:400]}")

print("RESULT:", "PASS" if failures == 0 else f"FAIL ({failures})")
sys.exit(1 if failures else 0)
