"""On-chip GQA-kernel evidence sized for a short live window.

The GQA-native flash path (grouped K/V heads mapped in-kernel, never
repeated in HBM — ops/attention.py) is pinned in interpret mode by
tests/test_ops.py, but interpret mode has already missed one Mosaic
lowering bug (round 2), so the verdict wants the *compiled* path proven
on silicon.  The full `pytest -m tpu -k gqa` tier needs a longer window
than the tunnel usually grants; this probe captures the same evidence —
compiled fwd+grads numerics vs the widened f32 reference, plus wall time
vs the repeat-K/V XLA path — in one ~2-minute incremental-emission run.

Usage: python build/micro_gqa_probe.py [out.json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else "artifacts/micro_gqa.json"


def emit(doc):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, OUT)


def main():
    t0 = time.time()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.attention import (
        _on_tpu, flash_attention, xla_attention,
    )

    b, h, kv_h, t, d = 1, 8, 2, 1024, 64
    doc = {
        "platform": jax.devices()[0].platform,
        "devices": len(jax.devices()),
        "on_tpu": _on_tpu(),
        "shape": {"b": b, "h": h, "kv_heads": kv_h, "t": t, "d": d},
        "connect_sec": round(time.time() - t0, 1),
    }
    emit(doc)
    if not doc["on_tpu"]:
        doc["note"] = "not on TPU; compiled-kernel evidence needs the chip"
        emit(doc)
        print(json.dumps(doc))
        return

    group = h // kv_h
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(keys[0], (b, h, t, d)).astype(jnp.bfloat16)
    k = jax.random.normal(keys[1], (b, kv_h, t, d)).astype(jnp.bfloat16)
    v = jax.random.normal(keys[2], (b, kv_h, t, d)).astype(jnp.bfloat16)

    # Widened f32 reference: repeat K/V to full heads in HBM, XLA attention
    # (same oracle as tests/test_ops.py::test_gqa_compiled).
    def widened(q32, k32, v32):
        return xla_attention(
            q32, jnp.repeat(k32, group, axis=1),
            jnp.repeat(v32, group, axis=1), causal=True)

    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))

    # --- compiled forward numerics (same tolerance shape as the oracle in
    # tests/test_ops.py::test_gqa_compiled: atol + rtol * |ref|) ---
    def close(x, r, atol, rtol):
        return bool(jnp.all(jnp.abs(x - r) <= atol + rtol * jnp.abs(r)))

    out = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))(q, k, v)
    ref = widened(qf, kf, vf)
    fwd_err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)))
    doc.update(fwd_max_abs_err=round(fwd_err, 5),
               fwd_ok=close(out.astype(jnp.float32), ref, 0.05, 0.05),
               kernel_path="pallas")
    emit(doc)

    # --- compiled grads numerics ---
    def loss(attn, *args):
        return jnp.sum(attn(*args).astype(jnp.float32) ** 2)

    grads = jax.jit(jax.grad(
        lambda q, k, v: loss(lambda *a: flash_attention(*a, True), q, k, v),
        argnums=(0, 1, 2)))(q, k, v)
    refs = jax.jit(jax.grad(
        lambda q, k, v: loss(widened, q, k, v), argnums=(0, 1, 2)))(qf, kf, vf)
    rel_errs = {}
    ok = True
    for name, g, r in zip(("dq", "dk", "dv"), grads, refs):
        denom = float(jnp.max(jnp.abs(r))) or 1.0
        rel_errs[name] = round(
            float(jnp.max(jnp.abs(g.astype(jnp.float32) - r))) / denom, 5)
        # test_gqa_compiled's grad tolerance: atol=0.1, rtol=0.1
        ok = ok and close(g.astype(jnp.float32), r, 0.1, 0.1)
    doc.update(grad_max_rel_err=rel_errs, grads_ok=ok)
    emit(doc)  # numerics safe on disk before the timing arms

    # --- timing: GQA flash (in-kernel grouping) vs repeat-K/V XLA ---
    def timed(fn, reps=3):
        grad = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))
        c0 = time.time()
        outv = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in outv])
        compile_sec = time.time() - c0
        t1 = time.perf_counter()
        for _ in range(reps):
            outv = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in outv])
        return (time.perf_counter() - t1) / reps * 1e3, compile_sec

    flash_ms, flash_compile = timed(
        lambda q, k, v: flash_attention(q, k, v, True))
    doc.update(flash_ms=round(flash_ms, 3),
               flash_compile_sec=round(flash_compile, 1))
    emit(doc)

    xla_ms, xla_compile = timed(
        lambda q, k, v: xla_attention(
            q, jnp.repeat(k, group, axis=1), jnp.repeat(v, group, axis=1),
            causal=True))
    doc.update(xla_ms=round(xla_ms, 3), xla_compile_sec=round(xla_compile, 1),
               speedup=round(xla_ms / flash_ms, 3),
               total_sec=round(time.time() - t0, 1))
    emit(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
