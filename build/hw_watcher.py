#!/usr/bin/env python
"""Opportunistic hardware-evidence watcher.

The tunneled TPU backend is flaky (see artifacts/ROUND3_NOTES.md: a wedge
can last hours, with occasional ~1-minute live windows).  This watcher
loops: probe the backend in a subprocess (a wedged tunnel hangs `import
jax` itself, so the probe must be a killable child), and when it is live,
burn down the pending hardware-evidence list in priority order:

  1. the micro probes (build/micro_tpu_probe.py, micro_gqa_probe.py,
     micro_lm_probe.py, micro_window_probe.py) — each sized for a ~1-2
     minute window; together they cover flash-vs-XLA perf, compiled-GQA
     numerics+perf, LM tokens/sec+MFU, and the banded sliding-window/
     sink kernels on chip even if no window ever fits the bench
  2. full bench with the LM model first (LM tokens/sec + MFU, then the
     flash-vs-XLA attention ladder, then the second model) -> bench JSON
  3. GQA compiled kernel tests (`pytest -m tpu -k gqa`)
  4. the full TPU test tier (`pytest -m tpu`, in two budgeted chunks)

Every capture goes to a temp file first and only replaces the artifact
when the capture is non-empty and (for the bench) parses as JSON — a
mid-run wedge must never truncate previously recorded evidence.  Partial
bench runs (stage timeouts flagged via `partial_rc` by bench.py) are kept
under a `_partial` name and the stage is retried on the next live window.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(ROOT, "artifacts")
STAMP = sys.argv[1] if len(sys.argv) > 1 else "r04"
MAX_SECONDS = float(os.environ.get("HW_WATCHER_MAX_SECONDS", 11.0 * 3600))
PROBE_INTERVAL = float(os.environ.get("HW_WATCHER_PROBE_INTERVAL", 60))

BENCH = os.path.join(ART, f"bench_{STAMP}.json")
GQA = os.path.join(ART, f"gqa_tpu_{STAMP}.log")
# The full tier is captured in two chunks (kernel/ops tests first —
# both here and in build/tpu_hw_check.sh): on a slow tunnel one heavy
# test can eat a whole window, and the chunks partition `-m tpu`
# exactly, so a green ops+rest pair IS a full-tier capture.  TIER (the
# single-file name) is accepted for legacy whole-tier captures (e.g. a
# hand-recorded tpu_tier_r03.log) but no longer written by any path.
TIER = os.path.join(ART, f"tpu_tier_{STAMP}.log")
TIER_OPS = os.path.join(ART, f"tpu_tier_ops_{STAMP}.log")
TIER_REST = os.path.join(ART, f"tpu_tier_rest_{STAMP}.log")
MICRO = os.path.join(ART, f"micro_flash_{STAMP}.json")
# Window-sized companions to the flash micro (see build/micro_*_probe.py):
# compiled-GQA numerics+timing, LM tokens/sec+MFU, and the banded
# sliding-window/sink kernels — together they cover the on-chip evidence
# set even if no tunnel window ever fits the bench.
MICRO_GQA = os.path.join(ART, f"micro_gqa_{STAMP}.json")
MICRO_LM = os.path.join(ART, f"micro_lm_{STAMP}.json")
MICRO_WIN = os.path.join(ART, f"micro_window_{STAMP}.json")
# The T-sweep probe is RESUMABLE (build/micro_sweep_probe.py): it reloads
# its own partial output and burns down remaining rungs, so unlike the
# other micros it must never be parked aside between windows.
MICRO_SWEEP = os.path.join(ART, f"micro_sweep_{STAMP}.json")


def log(msg: str) -> None:
    print(f"[{time.strftime('%H:%M:%S', time.gmtime())}] {msg}", flush=True)


def run(cmd, timeout, env=None):
    """Run cmd, return (rc, stdout, stderr); rc=None on timeout.  stdout is
    kept separate — bench.py's one JSON line goes to stdout and must not be
    buried under trailing stderr warnings."""
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    try:
        r = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            env=full_env, cwd=ROOT)
        return r.returncode, r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired as e:
        out, err = e.stdout or b"", e.stderr or b""
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        if isinstance(err, bytes):
            err = err.decode("utf-8", "replace")
        return None, out, err


def probe() -> bool:
    rc, out, _err = run(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].platform)"],
        timeout=90)
    return rc == 0 and "tpu" in out.lower()


def bench_complete(path: str) -> bool:
    """A bench capture counts as done only if it ran on TPU, produced a
    nonzero headline, and no stage was cut short by a tunnel wedge.

    Truncation is judged on the DOC-level partial flags (headline,
    second-model, attention + its arms): bench.py marks partials on the
    parsed result docs (`partial_rc`, bench.py:211,250), while its stage
    entries record a timeout as rc=-9 — and a late rung can legitimately
    complete after an earlier rung timed out, so stage rc alone can't
    distinguish 'ladder recovered' from 'ladder truncated'."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    stages = doc.get("stages", [])
    on_tpu = any(s.get("stage") == "probe" and s.get("ok")
                 and "tpu" in str(s.get("platform", "")).lower()
                 for s in stages)
    skipped = any(s.get("skipped") for s in stages
                  if str(s.get("stage", "")).startswith(
                      ("throughput", "attention")))
    partial = bool(doc.get("partial_rc") or doc.get("error"))
    # The second model is corroboration the watcher's bench always runs
    # (it never sets BENCH_SKIP_SECOND_MODEL): absent entirely means every
    # rung of its ladder died, which must not promote as complete.
    other = "resnet" if str(doc.get("metric", "")).startswith("lm") else "lm"
    if not isinstance(doc.get(other), dict):
        partial = True
    for sub in ("lm", "resnet"):
        if isinstance(doc.get(sub), dict) and doc[sub].get("partial_rc"):
            partial = True
    att = doc.get("attention")
    if not isinstance(att, dict):
        partial = True  # ladder never produced rows at all
    else:
        for arm in (att, att.get("gqa_arm"), att.get("window_arm")):
            if isinstance(arm, dict) and arm.get("partial_rc"):
                partial = True
    return on_tpu and doc.get("value", 0) > 0 and not (partial or skipped)


def next_partial(dst: str) -> str:
    """First free `<stem>_partialN.<ext>` next to dst — the shared
    retention convention for captures that are worth keeping but must
    not retire a stage (build/tpu_hw_check.sh uses the same names)."""
    stem, ext = os.path.splitext(dst)
    n = 1
    while os.path.exists(f"{stem}_partial{n}{ext}"):
        n += 1
    return f"{stem}_partial{n}{ext}"


def do_bench() -> bool:
    log("stage bench: starting (BENCH_MODEL=lm first)")
    rc, out, _err = run([sys.executable, "bench.py"], timeout=3900,
                        env={"BENCH_MODEL": "lm",
                             "BENCH_ATTENTION_FIRST": "1"})
    lines = [ln for ln in out.strip().splitlines() if ln.strip()]
    if not lines:
        log(f"stage bench: no output (rc={rc})")
        return False
    tmp = os.path.join(ART, ".bench_watch.tmp")
    with open(tmp, "w") as f:
        f.write(lines[-1] + "\n")
    if bench_complete(tmp):
        os.replace(tmp, BENCH)
        log(f"stage bench: COMPLETE -> {BENCH}")
        return True
    # keep flagged partials under a distinct name; retry next window
    try:
        json.loads(lines[-1])
    except ValueError:
        log(f"stage bench: last line not JSON (rc={rc}); dropped")
        os.unlink(tmp)
        return False
    dst = next_partial(BENCH)
    os.replace(tmp, dst)
    log(f"stage bench: partial -> {dst}; will retry")
    return False


def do_pytest(expr, timeout, dest, label, paths=("tests/",), extra=()) -> bool:
    log(f"stage {label}: starting")
    cmd = [sys.executable, "-m", "pytest", *paths, "-m", "tpu", "-v", *extra]
    if expr:
        cmd += ["-k", expr]
    rc, out, err = run(cmd, timeout=timeout,
                       env={"TPUJOB_TEST_PLATFORM": "tpu"})
    # Judge green on pytest's stdout (where the summary line lives) —
    # the tunneled backend floods stderr with xla/libtpu warnings, and a
    # combined-stream tail can evict the summary, making a passing run
    # look forever incomplete.  The artifact keeps stdout's tail first
    # so stage_done's re-read reaches the same verdict, plus a short
    # stderr tail for diagnosis.
    tail = "\n".join(out.strip().splitlines()[-40:])
    if err.strip():
        tail += f"\n{STDERR_MARKER}\n" + "\n".join(
            err.strip().splitlines()[-10:])
    if rc == 0 and tail_green(out):
        tmp = dest + ".tmp"
        with open(tmp, "w") as f:
            f.write(tail + "\n")
        os.replace(tmp, dest)
        log(f"stage {label}: COMPLETE -> {dest}")
        return True
    log(f"stage {label}: failed (rc={rc}); tail: {tail[-300:]!r}")
    return False


def do_micro(script: str, out_path: str, label: str,
             resumable: bool = False) -> bool:
    """A ~1-2 minute-window stage: one of the build/micro_*_probe.py
    scripts, all of which emit their JSON incrementally (a window dying
    mid-run still leaves the earlier arms on disk).  `resumable` probes
    reload their own partial output and continue, so their partials stay
    at the final name instead of being parked aside."""
    log(f"stage {label}: starting")
    rc, out, err = run([sys.executable, script, out_path], timeout=420)
    done = micro_complete(out_path)
    try:
        with open(out_path) as f:
            log(f"stage {label}: rc={rc} doc={json.load(f)}")
    except (OSError, ValueError):
        log(f"stage {label}: no artifact (rc={rc}); err tail: {err[-200:]!r}")
    if not done and not resumable and os.path.exists(out_path):
        # keep a partial under another name; retry for the full run
        os.replace(out_path, next_partial(out_path))
    return done


def tail_green(tail: str) -> bool:
    """A pytest tail counts as green only on a real summary line: some
    tests passed, none failed or errored.  (Substring checks are not
    enough: 'passed' appears in failing summaries too, and a bare
    'error' match would flag harmless warning text mentioning an Error
    class, making a good capture look forever incomplete.)"""
    return (re.search(r"\b\d+ passed\b", tail) is not None
            and re.search(r"\b\d+ (failed|error)", tail) is None)


# Captured artifacts may embed a stderr tail for diagnosis after this
# marker; green-judging must only see the stdout part, or a stray
# backend warning like "compilation: 1 error(s)" would flip a recorded
# green capture back to not-done and burn every live window re-running it.
STDERR_MARKER = "--- stderr tail ---"


def file_green(path: str) -> bool:
    try:
        with open(path) as f:
            content = f.read()
    except OSError:
        return False
    return tail_green(content.split(STDERR_MARKER)[0])


def micro_complete(path: str) -> bool:
    """Single source of truth for micro-probe completeness, used both by
    do_micro (retention) and stage_done (retirement): the probes write
    their JSON incrementally, so a mid-stage kill can leave an incomplete
    doc at the final name.  Every build/micro_*_probe.py emits
    `total_sec` only in its final on-chip emit, so on_tpu + total_sec
    means the run reached the end."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    return bool(doc.get("on_tpu")) and "total_sec" in doc


def stage_done(p: str) -> bool:
    """An artifact only retires its stage when it is a *complete* TPU
    capture: a CPU-fallback bench (all probes timed out) or a
    timeout-truncated pytest tail must not block retries on the next
    live window."""
    if p == BENCH:
        return bench_complete(p)
    if p == TIER:
        return (file_green(p)
                or (file_green(TIER_OPS) and file_green(TIER_REST)))
    if p == GQA:
        return file_green(p)
    if p in (MICRO, MICRO_GQA, MICRO_LM, MICRO_WIN, MICRO_SWEEP):
        return micro_complete(p)
    return os.path.exists(p)


def main() -> None:
    os.makedirs(ART, exist_ok=True)
    start = time.time()
    log(f"watcher up, stamp={STAMP}, budget={MAX_SECONDS / 3600:.1f}h")
    while time.time() - start < MAX_SECONDS:
        pending = [p for p in (MICRO, MICRO_GQA, MICRO_LM, MICRO_WIN,
                               MICRO_SWEEP, BENCH, GQA, TIER)
                   if not stage_done(p)]
        if not pending:
            log("ALL_DONE: every artifact recorded")
            return
        if probe():
            log(f"tunnel LIVE; pending: {[os.path.basename(p) for p in pending]}")
            # micros first: they fit in windows nothing else can use,
            # and together (flash perf, GQA-compiled numerics+perf, LM
            # tokens/sec+MFU, banded window/sink kernels) they cover the
            # on-chip evidence set even if no window ever fits the bench.
            if not stage_done(MICRO):
                do_micro("build/micro_tpu_probe.py", MICRO, "micro")
            if not stage_done(MICRO_GQA) and probe():
                do_micro("build/micro_gqa_probe.py", MICRO_GQA, "micro-gqa")
            if not stage_done(MICRO_LM) and probe():
                do_micro("build/micro_lm_probe.py", MICRO_LM, "micro-lm")
            if not stage_done(MICRO_WIN) and probe():
                do_micro("build/micro_window_probe.py", MICRO_WIN,
                         "micro-window")
            if not stage_done(MICRO_SWEEP) and probe():
                do_micro("build/micro_sweep_probe.py", MICRO_SWEEP,
                         "micro-sweep", resumable=True)
            if not stage_done(BENCH) and probe():
                do_bench()
            if not stage_done(GQA) and probe():
                do_pytest("gqa", 1200, GQA, "gqa")
            if not stage_done(TIER) and probe():
                # Burn down only the missing chunk(s): re-running already
                # captured heavy kernel tests wastes a live window that
                # might fit just the remainder.
                if not file_green(TIER_OPS):
                    do_pytest(None, 900, TIER_OPS, "tier-ops",
                              paths=("tests/test_ops.py",))
                if not file_green(TIER_REST) and probe():
                    do_pytest(None, 900, TIER_REST, "tier-rest",
                              extra=("--ignore=tests/test_ops.py",))
        else:
            log("tunnel dead")
        time.sleep(PROBE_INTERVAL)
    log("budget exhausted; exiting")


if __name__ == "__main__":
    main()
