"""On-chip T-sweep: flash-vs-XLA + banded-window scaling + gated autotune.

VERDICT r04 asks for two curves no single ~1-minute tunnel window can
produce: (2) the autotuned flash speedup at t in {1024, 4096, 8192}
(bar: >=1.2x at t>=4096) and (3) the banded sliding-window kernel's
window_speedup growing with T at fixed w (bar: >=2x by t=8192, proving
the O(T*w)-vs-O(T^2) DMA claim in ops/attention.py:50-62).

So unlike the sibling micro probes this one is RESUMABLE: it loads its
own output file, computes the remaining work units, and burns down as
many as the window allows, emitting after every measurement.  The
watcher re-invokes it (without parking the partial aside) until the
unit list is empty, at which point `total_sec` lands and the stage
retires (hw_watcher.micro_complete).

Work units, in evidence-value order:
  t4096 flash+xla speedup        (the headline rung)
  t4096 window arm               (window_speedup mid-curve)
  t8192 window arm               (the >=2x claim)
  t8192 flash+xla speedup        (XLA may OOM at O(T^2) — that IS data)
  t1024 flash+xla speedup        (curve anchor)
  t1024 window arm               (curve anchor)
  autotune at any rung with speedup < 1.2 (largest t first, trimmed
  candidate list, persisted via TPUJOB_AUTOTUNE_CACHE)

Usage: python build/micro_sweep_probe.py [out.json]
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = sys.argv[1] if len(sys.argv) > 1 else "artifacts/micro_sweep.json"
B, H, D = 1, 8, 64
WINDOW = 512
SEQS = (4096, 8192, 1024)
TUNE_TARGET = 1.2
# trimmed from ops/autotune.DEFAULT_CANDIDATES: drop the (128,128)
# default (already measured as flash_ms) and the most VMEM-hungry combos
TUNE_CANDIDATES = [(256, 128), (128, 256), (256, 256), (512, 256),
                   (256, 512), (512, 512)]


class TransientBackendError(Exception):
    """A failure that says nothing about the kernel — a dropped tunnel,
    gRPC deadline, dead coordinator.  The unit must stay PENDING (no
    per-unit error key) so the next live window retries it; recording it
    would retire the unit and, eventually, the whole stage with no real
    measurement."""


def _is_oom(e) -> bool:
    """True for failures that ARE data at this shape: VMEM/HBM exhaustion
    or a Mosaic lowering rejection — stable properties of (kernel, shape),
    not of the flaky tunnel."""
    s = repr(e)
    return any(m in s for m in (
        "RESOURCE_EXHAUSTED", "Resource exhausted", "Out of memory", "OOM",
        "VMEM", "Mosaic", "lowering"))


def load(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def emit(doc):
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, OUT)


def pending_units(doc):
    """Remaining work units for a (possibly partial) sweep doc, in
    evidence-value order.  Pure: unit-testable off-chip."""
    rungs = doc.get("rungs") or {}

    def rung(t):
        return rungs.get(str(t)) or {}

    units = []
    for t, kind in ((4096, "speed"), (4096, "window"), (8192, "window"),
                    (8192, "speed"), (1024, "speed"), (1024, "window")):
        r = rung(t)
        if kind == "speed":
            # done when both arms have a timing or a recorded error
            if not (("flash_ms" in r or "flash_error" in r)
                    and ("xla_ms" in r or "xla_error" in r)):
                units.append((kind, t))
        else:
            if not ("window_ms" in r or "window_error" in r):
                units.append((kind, t))
    # autotune only where the measured default tiling missed the bar
    for t in sorted(SEQS, reverse=True):
        r = rung(t)
        speedup = r.get("speedup")
        if (speedup is not None and speedup < TUNE_TARGET
                and "tuned_blocks" not in r and "autotune_error" not in r):
            units.append(("tune", t))
    return units


def main():
    t0 = time.time()
    from tf_operator_tpu.workloads.runner import apply_forced_platform

    apply_forced_platform()
    os.environ.setdefault(
        "TPUJOB_AUTOTUNE_CACHE",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "artifacts", "autotune_cache.json"))
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.attention import (
        _on_tpu, flash_attention, repeat_kv, xla_attention,
    )

    doc = load(OUT) or {}
    doc.update(
        platform=jax.devices()[0].platform,
        devices=len(jax.devices()),
        on_tpu=_on_tpu(),
        shape={"b": B, "h": H, "d": D, "window": WINDOW},
    )
    doc.setdefault("rungs", {})
    doc.setdefault("connect_sec", round(time.time() - t0, 1))
    doc.pop("total_sec", None)  # re-judged below
    emit(doc)
    if not doc["on_tpu"]:
        doc["note"] = "not on TPU; sweep evidence needs the chip"
        emit(doc)
        print(json.dumps(doc))
        return

    def tensors(t):
        keys = jax.random.split(jax.random.PRNGKey(7), 3)
        q = jax.random.normal(keys[0], (B, H, t, D)).astype(jnp.bfloat16)
        k = jax.random.normal(keys[1], (B, H, t, D)).astype(jnp.bfloat16)
        v = jax.random.normal(keys[2], (B, H, t, D)).astype(jnp.bfloat16)
        return q, k, v

    def timed(fn, t, reps=3):
        q, k, v = tensors(t)
        grad = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
            argnums=(0, 1, 2)))
        out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        t1 = time.perf_counter()
        for _ in range(reps):
            out = grad(q, k, v)
        jax.device_get([jnp.sum(x.astype(jnp.float32)) for x in out])
        return (time.perf_counter() - t1) / reps * 1e3

    def refresh_rung(t):
        r = doc["rungs"].setdefault(str(t), {})
        if r.get("flash_ms") and r.get("xla_ms"):
            r["speedup"] = round(r["xla_ms"] / r["flash_ms"], 3)
        if r.get("flash_ms") and r.get("window_ms"):
            r["window_speedup"] = round(r["flash_ms"] / r["window_ms"], 3)
        return r

    def measure(r, key, fn, t):
        """Time fn at t into r[key].  OOM/lowering failures are data and
        retire the arm via its _error key; anything else (dead tunnel)
        raises TransientBackendError so the unit stays pending."""
        if key in r or key.replace("_ms", "_error") in r:
            return
        try:
            r[key] = round(timed(fn, t), 3)
            if key == "flash_ms":
                r["kernel_path"] = "pallas"
        except Exception as e:  # noqa: BLE001 — classified below
            if _is_oom(e):
                r[key.replace("_ms", "_error")] = repr(e)[:200]
            else:
                raise TransientBackendError(repr(e)[:300]) from e
        finally:
            refresh_rung(t)
            emit(doc)

    try:
        while True:
            units = pending_units(doc)
            if not units:
                break
            kind, t = units[0]
            r = doc["rungs"].setdefault(str(t), {})
            if kind == "speed":
                measure(r, "flash_ms",
                        lambda q, k, v: flash_attention(q, k, v, True), t)
                measure(r, "xla_ms",
                        lambda q, k, v: xla_attention(
                            q, *repeat_kv(q, k, v), causal=True), t)
            elif kind == "window":
                # the window arm is priced against full flash at the same t
                measure(r, "flash_ms",
                        lambda q, k, v: flash_attention(q, k, v, True), t)
                measure(r, "window_ms",
                        lambda q, k, v: flash_attention(
                            q, k, v, True, window=WINDOW), t)
            elif kind == "tune":
                from tf_operator_tpu.ops.autotune import tune_flash_blocks

                tuned = tune_flash_blocks(
                    B, H, t, D, causal=True, reps=3,
                    candidates=TUNE_CANDIDATES)
                if "block_q" in tuned:
                    r["tuned_blocks"] = [tuned["block_q"], tuned["block_k"]]
                    measure(r, "flash_tuned_ms",
                            lambda q, k, v: flash_attention(
                                q, k, v, True, None,
                                tuned["block_q"], tuned["block_k"]), t)
                    if r.get("xla_ms") and r.get("flash_tuned_ms"):
                        r["speedup_tuned"] = round(
                            r["xla_ms"] / r["flash_tuned_ms"], 3)
                else:
                    # tune_flash_blocks swallows per-candidate exceptions
                    # into its table; only OOM/lowering table entries prove
                    # the search itself failed (data).  An all-transient
                    # table (dead tunnel) must leave the unit pending.
                    errs = [c.get("error", "")
                            for c in tuned.get("table", [])]
                    if any(_is_oom(RuntimeError(s)) for s in errs if s):
                        r["autotune_error"] = tuned.get("error", "")[:200]
                    else:
                        raise TransientBackendError(
                            f"autotune: no candidate compiled and no "
                            f"shape-level error in table: {errs[:2]!r}")
                emit(doc)
    except TransientBackendError as e:
        doc["last_transient_error"] = str(e)
        emit(doc)
        print(json.dumps(doc))
        return  # no total_sec: the stage stays pending for the next window

    doc["total_sec"] = round(time.time() - t0, 1)
    emit(doc)
    print(json.dumps(doc))


if __name__ == "__main__":
    main()
